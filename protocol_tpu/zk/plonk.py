"""PLONK proving system over the framework's main gate, with lookups.

The reference proves with halo2 (PSE fork): a PLONKish arithmetization
whose core is the ``MainChip`` 5-advice/8-fixed gate
(``eigentrust-zk/src/gadgets/main.rs:1-113``)

    q_a·a + q_b·b + q_c·c + q_d·d + q_e·e
      + q_mul_ab·a·b + q_mul_cd·c·d + q_const = 0

plus equality (copy) constraints, instance columns, and halo2's lookup
argument (the reference's range chips are lookup-based,
``gadgets/range.rs``). This module is a from-scratch implementation of
that proving-stack shape on the framework's own KZG/BN254 layer
(``kzg.py``/``bn254.py``):

- the same 5-wire main gate (so every MainChip-style gadget ports 1:1),
- a 6th wire reserved as the **lookup input column**: every row's wire-5
  value must appear in a fixed range table [0, 2^lookup_bits). Rows that
  don't use the lookup leave it 0. The argument is LogUp (log-derivative
  lookups): Σ 1/(β+aᵢ) = Σ mᵢ/(β+tᵢ) enforced through a running-sum
  column φ and a multiplicity column m — two extra commitments, same
  power as halo2's sorted-permutation lookup with simpler bookkeeping.
- copy constraints via the PLONK permutation argument (6-coset grand
  product; the lookup wire participates, so range-checked cells can be
  copy-wired like any other),
- public inputs as a PI(X) polynomial folded into the gate,
- GWC-style batched KZG openings at {x, ωx},
- Poseidon Fiat–Shamir transcript (``transcript.py``),
- blinding by multiples of Z_H (GWC19), so identities hold on all of H.

``check_satisfied`` is the MockProver twin: the reference's test
strategy runs every circuit through ``MockProver::assert_satisfied``
(SURVEY.md §4 pattern 1-2); large circuits here do the same while real
prove/verify runs cover small instances (the reference `#[ignore]`s its
real-prover tests for the same cost reason, §4.4).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..utils.errors import EigenError
from ..utils.fields import BN254_FR_MODULUS
from .domain import EvaluationDomain, poly_eval
from .kzg import (
    KZGParams,
    decide,
    fold_batch,
    g1_from_bytes,
    g1_to_bytes,
    open_batch,
)
from .transcript import PoseidonTranscript, make_transcript

R = BN254_FR_MODULUS

SELECTORS = ("q_a", "q_b", "q_c", "q_d", "q_e", "q_mul_ab", "q_mul_cd", "q_const")
FIXED_NAMES = SELECTORS + ("t_lookup",)
NUM_WIRES = 6  # 5 gate wires + 1 lookup input column
LOOKUP_WIRE = 5
# z-split permutation argument (r4): the degree-7 grand-product
# constraint z(ωX)·Πg_w = z(X)·Πf_w is split through four committed
# partial-product columns u1 = z·f0·f1, u2 = u1·f2·f3, v1 = z(ωX)·g0·g1,
# v2 = v1·g2·g3 plus the link u2·f4·f5 = v2·g4·g5 — every quotient term
# has ≤ 3 polynomial factors (max total degree 3n+5 with blinding), so
# the extension coset shrinks from 8n to 4n and t from 7 chunks to 3.
# No new opening rotations: u/v open at ζ only; z(ωζ) was already open.
NUM_PERM_PARTIALS = 4
EXT_FACTOR_LOG = 2  # quotient runs on a 4n coset (was 8n pre-split)
QUOTIENT_CHUNKS = 3  # t degree ≤ 2n+5 after the z-split
MIN_K = 4  # max identity degree 3n+5 must stay under 4n


class ConstraintSystem:
    """Row-based circuit builder: wires + selectors + copies + publics.

    Cells are (wire, row) pairs. ``add_row`` appends a gate row; wires
    default to 0 and selectors to 0, so padding rows trivially satisfy
    the gate. Wire 5 is the lookup input column: if ``lookup_bits`` is
    set, every row's wire-5 value must lie in [0, 2^lookup_bits); when
    unset the only legal value is 0 (the table is {0}).
    """

    def __init__(self, lookup_bits: int | None = None):
        self.wires: list = [[] for _ in range(NUM_WIRES)]
        # sparse: selector name -> {row: value}; unset rows are 0 (the
        # overwhelmingly common case at multi-million-row scale)
        self.selectors: dict = {name: {} for name in SELECTORS}
        self.copies: list = []
        self.public_rows: list = []  # (row, value); value lives in wire 0
        self.lookup_bits = lookup_bits

    @property
    def num_rows(self) -> int:
        return len(self.wires[0])

    def add_row(self, values=(), **selectors) -> int:
        # hot path: circuits run to millions of rows, so only the
        # selectors actually passed are touched; all validation happens
        # before any column is mutated
        wires = self.wires
        sel = self.selectors
        if len(values) > NUM_WIRES:
            raise EigenError("circuit_error",
                             f"row takes at most {NUM_WIRES} values")
        if selectors:
            for name in selectors:
                if name not in sel:
                    raise EigenError("circuit_error",
                                     f"unknown selector {name}")
        row = len(wires[0])
        i = 0
        for v in values:
            if type(v) is not int:
                v = int(v)
            if not 0 <= v < R:
                v %= R
            wires[i].append(v)
            i += 1
        while i < NUM_WIRES:
            wires[i].append(0)
            i += 1
        if selectors:
            for name, v in selectors.items():
                if type(v) is not int:
                    v = int(v)
                if not 0 <= v < R:
                    v %= R
                if v:
                    sel[name][row] = v
        return row

    def lookup_row(self, value: int) -> tuple:
        """A fresh row whose wire-5 cell carries ``value`` (so it is
        constrained to the range table); returns that cell."""
        value = int(value) % R
        row = self.add_row([0, 0, 0, 0, 0, value])
        return (LOOKUP_WIRE, row)

    def copy(self, cell_a, cell_b) -> None:
        """Equality-constrain two cells; values must already agree."""
        (wa, ra), (wb, rb) = cell_a, cell_b
        if self.wires[wa][ra] != self.wires[wb][rb]:
            raise EigenError(
                "circuit_error",
                f"copy constraint between unequal cells {cell_a}={self.wires[wa][ra]}"
                f" and {cell_b}={self.wires[wb][rb]}",
            )
        self.copies.append((cell_a, cell_b))

    def public_input(self, value: int) -> int:
        """Dedicated row `a − value = 0`; returns the row (cell (0, row))."""
        value = int(value) % R
        row = self.add_row([value], q_a=1)
        self.public_rows.append(row)
        return row

    def public_values(self) -> list:
        return [self.wires[0][row] for row in self.public_rows]

    # --- MockProver twin --------------------------------------------------
    def check_satisfied(self, public_inputs=None) -> None:
        """Raise EigenError on the first unsatisfied row/copy/public/lookup."""
        pubs = list(public_inputs) if public_inputs is not None else self.public_values()
        if len(pubs) != len(self.public_rows):
            raise EigenError("circuit_error", "public input arity mismatch")
        s = self.selectors
        w0, w1, w2, w3, w4, w5 = self.wires
        table_max = 1 << self.lookup_bits if self.lookup_bits else 1
        # rows with no selector entry satisfy the gate trivially: only
        # touched rows accumulate (sparse walk, one pass per selector)
        sums: dict = {}
        get = sums.get
        for i, v in s["q_a"].items():
            sums[i] = get(i, 0) + v * w0[i]
        for i, v in s["q_b"].items():
            sums[i] = get(i, 0) + v * w1[i]
        for i, v in s["q_c"].items():
            sums[i] = get(i, 0) + v * w2[i]
        for i, v in s["q_d"].items():
            sums[i] = get(i, 0) + v * w3[i]
        for i, v in s["q_e"].items():
            sums[i] = get(i, 0) + v * w4[i]
        for i, v in s["q_mul_ab"].items():
            sums[i] = get(i, 0) + v * w0[i] * w1[i]
        for i, v in s["q_mul_cd"].items():
            sums[i] = get(i, 0) + v * w2[i] * w3[i]
        for i, v in s["q_const"].items():
            sums[i] = get(i, 0) + v
        for row, value in zip(self.public_rows, pubs):
            sums[row] = sums.get(row, 0) - int(value)
        for i, acc in sums.items():
            if acc % R:
                raise EigenError("circuit_error",
                                 f"gate unsatisfied at row {i}")
        for i, lk in enumerate(w5):
            if lk >= table_max:
                raise EigenError(
                    "circuit_error",
                    f"lookup value at row {i} outside table "
                    f"[0, {table_max})",
                )
        wires = self.wires
        for (wa, ra), (wb, rb) in self.copies:
            if wires[wa][ra] != wires[wb][rb]:
                raise EigenError(
                    "circuit_error", f"copy violated: ({wa},{ra}) vs ({wb},{rb})"
                )


def _batch_inv(values: list) -> list:
    """Montgomery batch inversion; zeros map to zero."""
    prods = []
    acc = 1
    for v in values:
        prods.append(acc)
        if v:
            acc = acc * v % R
    inv = pow(acc, -1, R)
    out = [0] * len(values)
    for i in range(len(values) - 1, -1, -1):
        if values[i]:
            out[i] = inv * prods[i] % R
            inv = inv * values[i] % R
    return out


def _find_coset_shifts(n: int, count: int) -> list:
    """k₀=1 plus `count−1` values in distinct nontrivial cosets of H,
    checked directly (kᵢⁿ ≠ 1 and (kᵢ/kⱼ)ⁿ ≠ 1) rather than derived
    from number theory."""
    shifts = [1]
    candidate = 2
    while len(shifts) < count:
        ok = pow(candidate, n, R) != 1 and all(
            pow(candidate * pow(s, -1, R) % R, n, R) != 1 for s in shifts[1:]
        )
        if ok:
            shifts.append(candidate)
        candidate += 1
    return shifts


@dataclass
class ProvingKey:
    """Keygen output; doubles as the verifying key. Fixed and σ
    polynomials are committed at keygen (``vk_commits``) and their ζ
    evaluations ride the proof's batched KZG opening — halo2's actual
    protocol shape, and the property that makes succinct in-circuit
    verification possible (the aggregator never evaluates a 2^k-degree
    polynomial)."""

    k: int
    fixed_coeffs: dict  # selector name -> coeffs (includes "t_lookup")
    sigma_coeffs: list  # per wire
    sigma_evals: list  # per wire, row form (for the prover's z build)
    shifts: list
    public_rows: list
    lookup_bits: int | None
    vk_commits: dict  # FIXED_NAMES + "sigma_{w}" -> G1

    def domain(self) -> EvaluationDomain:
        return EvaluationDomain(self.k)

    def commit_list(self) -> list:
        """vk commitments in transcript/opening order."""
        return ([self.vk_commits[name] for name in FIXED_NAMES]
                + [self.vk_commits[f"sigma_{w}"] for w in range(NUM_WIRES)])

    def to_bytes(self) -> bytes:
        import json

        # sigma_evals is derivable (fft of sigma_coeffs) — never persisted,
        # so the two copies cannot disagree in a key file
        payload = {
            "k": self.k,
            "fixed": {name: coeffs for name, coeffs in self.fixed_coeffs.items()},
            "sigma": self.sigma_coeffs,
            "shifts": self.shifts,
            "public_rows": self.public_rows,
            "lookup_bits": self.lookup_bits,
            "vk_commits": {name: g1_to_bytes(pt).hex()
                           for name, pt in self.vk_commits.items()},
        }
        return json.dumps(payload).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ProvingKey":
        import json

        p = json.loads(data.decode())
        d = EvaluationDomain(p["k"])
        sigma_evals = [d.fft(c) for c in p["sigma"]]
        commits = {name: g1_from_bytes(bytes.fromhex(h))
                   for name, h in p["vk_commits"].items()}
        return cls(p["k"], p["fixed"], p["sigma"], sigma_evals,
                   p["shifts"], p["public_rows"], p.get("lookup_bits"),
                   commits)


def _table_values(lookup_bits: int | None, n: int) -> list:
    size = 1 << lookup_bits if lookup_bits else 1
    if size > n:
        raise EigenError(
            "circuit_error",
            f"lookup table 2^{lookup_bits} does not fit domain 2^k rows",
        )
    return list(range(size)) + [0] * (n - size)


def keygen(params: KZGParams, cs: ConstraintSystem,
           k: int | None = None) -> ProvingKey:
    """Fixed/σ polynomial construction + vk commitments (halo2
    ``keygen_pk`` equivalent, reference ``utils.rs:174-186``; same
    params-first argument order)."""
    rows = cs.num_rows
    if k is None:
        k = max(MIN_K, (max(rows, 1) - 1).bit_length())
        if cs.lookup_bits:
            k = max(k, cs.lookup_bits)
    if k < MIN_K:
        raise EigenError("circuit_error",
                         f"k={k} below minimum domain size k={MIN_K}")
    n = 1 << k
    if rows > n:
        raise EigenError("circuit_error", f"{rows} rows exceed 2^{k}")
    d = EvaluationDomain(k)

    fixed_coeffs = {}
    for name in SELECTORS:
        col = [0] * n
        for i, v in cs.selectors[name].items():
            col[i] = v
        fixed_coeffs[name] = d.ifft(col)
    fixed_coeffs["t_lookup"] = d.ifft(_table_values(cs.lookup_bits, n))

    # permutation: merge copy cycles with union-find + pointer swap
    shifts = _find_coset_shifts(n, NUM_WIRES)
    parent: dict = {}

    def find(x):
        while parent.get(x, x) != x:
            parent[x] = parent.get(parent[x], parent[x])
            x = parent[x]
        return x

    nxt = {}
    for w in range(NUM_WIRES):
        for r in range(n):
            nxt[(w, r)] = (w, r)
    for a, b in cs.copies:
        ra, rb = find(a), find(b)
        if ra == rb:
            continue
        parent[ra] = rb
        nxt[a], nxt[b] = nxt[b], nxt[a]

    omegas = d.elements()
    sigma_evals = []
    sigma_coeffs = []
    for w in range(NUM_WIRES):
        col = []
        for r in range(n):
            tw, tr = nxt[(w, r)]
            col.append(shifts[tw] * omegas[tr] % R)
        sigma_evals.append(col)
        sigma_coeffs.append(d.ifft(col))

    vk_commits = {name: params.commit(fixed_coeffs[name])
                  for name in FIXED_NAMES}
    for w in range(NUM_WIRES):
        vk_commits[f"sigma_{w}"] = params.commit(sigma_coeffs[w])

    return ProvingKey(k, fixed_coeffs, sigma_coeffs, sigma_evals, shifts,
                      list(cs.public_rows), cs.lookup_bits, vk_commits)


# --- proof object ---------------------------------------------------------

@dataclass
class Proof:
    wire_commits: list  # 6 G1
    m_commit: tuple  # lookup multiplicities
    z_commit: tuple
    phi_commit: tuple  # lookup running sum
    uv_commits: list  # 4 G1: z-split partials [u1, u2, v1, v2]
    t_commits: list  # QUOTIENT_CHUNKS G1
    wire_evals: list  # 6 at x
    m_eval: int
    z_eval: int
    z_next_eval: int
    phi_eval: int
    phi_next_eval: int
    uv_evals: list  # [u1, u2, v1, v2] at x
    t_evals: list  # chunks at x
    fixed_evals: list  # FIXED_NAMES at x (9)
    sigma_zeta: list  # σ_w at x (6)
    w_x: tuple  # batch witness at x
    w_wx: tuple  # batch witness at ωx

    def to_bytes(self) -> bytes:
        out = []
        for pt in (self.wire_commits + [self.m_commit, self.z_commit,
                                        self.phi_commit] + self.uv_commits
                   + self.t_commits):
            out.append(g1_to_bytes(pt))
        for v in (self.wire_evals
                  + [self.m_eval, self.z_eval, self.z_next_eval,
                     self.phi_eval, self.phi_next_eval]
                  + self.uv_evals + self.t_evals + self.fixed_evals
                  + self.sigma_zeta):
            out.append(int(v).to_bytes(32, "little"))
        out.append(g1_to_bytes(self.w_x))
        out.append(g1_to_bytes(self.w_wx))
        return b"".join(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Proof":
        npts = NUM_WIRES + 3 + NUM_PERM_PARTIALS + QUOTIENT_CHUNKS
        pts = [g1_from_bytes(data[i * 64 : (i + 1) * 64]) for i in range(npts)]
        off = npts * 64
        nf = len(FIXED_NAMES)
        nevals = (NUM_WIRES + 5 + NUM_PERM_PARTIALS + QUOTIENT_CHUNKS
                  + nf + NUM_WIRES)
        evals = [
            int.from_bytes(data[off + i * 32 : off + (i + 1) * 32], "little")
            for i in range(nevals)
        ]
        off += nevals * 32
        w_x = g1_from_bytes(data[off : off + 64])
        w_wx = g1_from_bytes(data[off + 64 : off + 128])
        w = NUM_WIRES
        np_ = NUM_PERM_PARTIALS
        uv_end = w + 5 + np_
        qe = uv_end + QUOTIENT_CHUNKS
        return cls(
            pts[:w], pts[w], pts[w + 1], pts[w + 2],
            pts[w + 3 : w + 3 + np_], pts[w + 3 + np_ :],
            evals[:w], evals[w], evals[w + 1], evals[w + 2], evals[w + 3],
            evals[w + 4], evals[w + 5 : uv_end], evals[uv_end : qe],
            evals[qe : qe + nf], evals[qe + nf :], w_x, w_wx,
        )


def _blind(coeffs: list, n: int, count: int) -> list:
    """Add (b₀ + b₁X + …)·Z_H — evaluations on H are unchanged, the
    polynomial is hidden (GWC19 blinding)."""
    out = list(coeffs) + [0] * (n + count - len(coeffs))
    for i in range(count):
        b = secrets.randbelow(R)
        out[i] = (out[i] - b) % R
        out[n + i] = (out[n + i] + b) % R
    return out


def _pi_evals(cs_public_rows, pubs, n) -> list:
    evals = [0] * n
    for row, value in zip(cs_public_rows, pubs):
        evals[row] = (-int(value)) % R
    return evals


def prove(params: KZGParams, pk: ProvingKey, cs: ConstraintSystem,
          public_inputs=None, transcript: str = "poseidon") -> bytes:
    d = pk.domain()
    n = d.n
    if cs.num_rows > n:
        raise EigenError("proving_error", "circuit larger than key domain")
    pubs = list(public_inputs) if public_inputs is not None else cs.public_values()
    tr = make_transcript(transcript)
    for v in pubs:
        tr.absorb_fr(v)

    # round 1: wire polynomials + lookup multiplicities
    wire_vals = [col + [0] * (n - cs.num_rows) for col in cs.wires]
    wire_coeffs = [_blind(d.ifft(col), n, 2) for col in wire_vals]
    wire_commits = [params.commit(c) for c in wire_coeffs]
    for cm in wire_commits:
        tr.absorb_point(cm)

    table = _table_values(pk.lookup_bits, n)
    table_size = 1 << pk.lookup_bits if pk.lookup_bits else 1
    m_vals = [0] * n
    for v in wire_vals[LOOKUP_WIRE]:
        if v >= table_size:
            raise EigenError("proving_error",
                             f"lookup value {v} outside range table")
        m_vals[v] += 1  # table[i] = i for i < table_size; zeros pool at row 0
    m_coeffs = _blind(d.ifft(m_vals), n, 2)
    m_commit = params.commit(m_coeffs)
    tr.absorb_point(m_commit)

    beta = tr.challenge()
    gamma = tr.challenge()
    beta_lk = tr.challenge()

    # round 2a: permutation grand product (individual wire factors kept
    # for the z-split partial products below)
    omegas = d.elements()
    f_factors = []  # f_w[i] = w_w + β·k_w·ωⁱ + γ
    g_factors = []  # g_w[i] = w_w + β·σ_w(ωⁱ) + γ
    for w in range(NUM_WIRES):
        kw = pk.shifts[w]
        sw = pk.sigma_evals[w]
        col = wire_vals[w]
        f_factors.append([(col[i] + beta * kw * omegas[i] + gamma) % R
                          for i in range(n)])
        g_factors.append([(col[i] + beta * sw[i] + gamma) % R
                          for i in range(n)])
    numer = [1] * n
    denom = [1] * n
    for w in range(NUM_WIRES):
        fw, gw = f_factors[w], g_factors[w]
        for i in range(n):
            numer[i] = numer[i] * fw[i] % R
            denom[i] = denom[i] * gw[i] % R
    denom_inv = _batch_inv(denom)
    z_vals = [1] * n
    for i in range(n - 1):
        z_vals[i + 1] = z_vals[i] * numer[i] % R * denom_inv[i] % R
    if z_vals[-1] * numer[-1] % R * denom_inv[-1] % R != 1:
        raise EigenError("proving_error", "permutation grand product does not wrap")
    z_coeffs = _blind(d.ifft(z_vals), n, 3)
    z_commit = params.commit(z_coeffs)
    tr.absorb_point(z_commit)

    # round 2b: LogUp running sum φ: φ₀ = 0,
    # φ_{i+1} = φ_i + 1/(β_lk + aᵢ) − mᵢ/(β_lk + tᵢ); wraps to 0.
    a_col = wire_vals[LOOKUP_WIRE]
    inv_a = _batch_inv([(beta_lk + v) % R for v in a_col])
    inv_t = _batch_inv([(beta_lk + v) % R for v in table])
    phi_vals = [0] * n
    for i in range(n - 1):
        phi_vals[i + 1] = (phi_vals[i] + inv_a[i] - m_vals[i] * inv_t[i]) % R
    if (phi_vals[-1] + inv_a[-1] - m_vals[-1] * inv_t[-1]) % R != 0:
        raise EigenError("proving_error", "lookup running sum does not wrap")
    phi_coeffs = _blind(d.ifft(phi_vals), n, 3)
    phi_commit = params.commit(phi_coeffs)
    tr.absorb_point(phi_commit)

    # round 2c: z-split partial products on H (u1, u2, v1, v2); note
    # z(ω·ωⁱ) on H is a cyclic roll of z_vals
    u1_vals = [z_vals[i] * f_factors[0][i] % R * f_factors[1][i] % R
               for i in range(n)]
    u2_vals = [u1_vals[i] * f_factors[2][i] % R * f_factors[3][i] % R
               for i in range(n)]
    v1_vals = [z_vals[(i + 1) % n] * g_factors[0][i] % R
               * g_factors[1][i] % R for i in range(n)]
    v2_vals = [v1_vals[i] * g_factors[2][i] % R * g_factors[3][i] % R
               for i in range(n)]
    uv_coeffs = [_blind(d.ifft(vals), n, 2)
                 for vals in (u1_vals, u2_vals, v1_vals, v2_vals)]
    uv_commits = [params.commit(c) for c in uv_coeffs]
    for cm in uv_commits:
        tr.absorb_point(cm)

    alpha = tr.challenge()

    # round 3: quotient on a 4n coset (the z-split caps every term at 3
    # polynomial factors)
    de = EvaluationDomain(pk.k + EXT_FACTOR_LOG)
    shift = _find_coset_shifts(de.n, 2)[1]

    def ext(coeffs):
        return de.coset_fft(coeffs, shift)

    wires_e = [ext(c) for c in wire_coeffs]
    z_e = ext(z_coeffs)
    zw_coeffs = [c * pow(d.omega, i, R) % R for i, c in enumerate(z_coeffs)]
    zw_e = ext(zw_coeffs)
    m_e = ext(m_coeffs)
    phi_e = ext(phi_coeffs)
    phiw_coeffs = [c * pow(d.omega, i, R) % R for i, c in enumerate(phi_coeffs)]
    phiw_e = ext(phiw_coeffs)
    uv_e = [ext(c) for c in uv_coeffs]
    fixed_e = {name: ext(c) for name, c in pk.fixed_coeffs.items()}
    sigma_e = [ext(c) for c in pk.sigma_coeffs]
    pi_e = ext(d.ifft(_pi_evals(pk.public_rows, pubs, n)))

    xs = []
    x = shift
    for _ in range(de.n):
        xs.append(x)
        x = x * de.omega % R
    zh = [(pow(x, n, R) - 1) % R for x in xs]
    zh_inv = _batch_inv(zh)
    l0_den = _batch_inv([n * (x - 1) % R for x in xs])

    t_evals_ext = []
    for i in range(de.n):
        a, b, c, dd, e = (wires_e[w][i] for w in range(5))
        gate = (
            fixed_e["q_a"][i] * a + fixed_e["q_b"][i] * b + fixed_e["q_c"][i] * c
            + fixed_e["q_d"][i] * dd + fixed_e["q_e"][i] * e
            + fixed_e["q_mul_ab"][i] * a * b + fixed_e["q_mul_cd"][i] * c * dd
            + fixed_e["q_const"][i] + pi_e[i]
        ) % R
        # z-split: wire factors at this point
        fv = [(wires_e[w][i] + beta * pk.shifts[w] * xs[i] + gamma) % R
              for w in range(NUM_WIRES)]
        gv = [(wires_e[w][i] + beta * sigma_e[w][i] + gamma) % R
              for w in range(NUM_WIRES)]
        u1, u2, v1, v2 = (uv_e[j][i] for j in range(4))
        link = (u2 * fv[4] % R * fv[5] - v2 * gv[4] % R * gv[5]) % R
        c_u1 = (u1 - z_e[i] * fv[0] % R * fv[1]) % R
        c_u2 = (u2 - u1 * fv[2] % R * fv[3]) % R
        c_v1 = (v1 - zw_e[i] * gv[0] % R * gv[1]) % R
        c_v2 = (v2 - v1 * gv[2] % R * gv[3]) % R
        l0 = zh[i] * l0_den[i] % R
        # LogUp: (φω − φ)(β+a)(β+t) − (β+t) + m(β+a) = 0 on H
        ba = (beta_lk + wires_e[LOOKUP_WIRE][i]) % R
        bt = (beta_lk + fixed_e["t_lookup"][i]) % R
        lk = ((phiw_e[i] - phi_e[i]) * ba % R * bt - bt + m_e[i] * ba) % R
        total = (
            gate
            + alpha * link
            + alpha * alpha % R * l0 * ((z_e[i] - 1) % R)
            + pow(alpha, 3, R) * lk
            + pow(alpha, 4, R) * l0 * phi_e[i]
            + pow(alpha, 5, R) * c_u1
            + pow(alpha, 6, R) * c_u2
            + pow(alpha, 7, R) * c_v1
            + pow(alpha, 8, R) * c_v2
        ) % R
        t_evals_ext.append(total * zh_inv[i] % R)

    t_coeffs = de.coset_ifft(t_evals_ext, shift)
    if any(c != 0 for c in t_coeffs[QUOTIENT_CHUNKS * n :]):
        raise EigenError(
            "proving_error",
            "quotient degree overflow — witness does not satisfy the circuit",
        )
    chunks = [t_coeffs[i * n : (i + 1) * n] for i in range(QUOTIENT_CHUNKS)]
    t_commits = [params.commit(ch) for ch in chunks]
    for cm in t_commits:
        tr.absorb_point(cm)
    zeta = tr.challenge()

    # round 4: evaluations (witness polys + the vk's fixed/σ polys — the
    # verifier checks the latter against the keygen commitments instead
    # of evaluating degree-2^k polynomials itself)
    wire_evals = [poly_eval(c, zeta) for c in wire_coeffs]
    m_eval = poly_eval(m_coeffs, zeta)
    z_eval = poly_eval(z_coeffs, zeta)
    z_next = poly_eval(z_coeffs, zeta * d.omega % R)
    phi_eval = poly_eval(phi_coeffs, zeta)
    phi_next = poly_eval(phi_coeffs, zeta * d.omega % R)
    uv_evals = [poly_eval(c, zeta) for c in uv_coeffs]
    t_evals = [poly_eval(ch, zeta) for ch in chunks]
    fixed_evals = [poly_eval(pk.fixed_coeffs[name], zeta)
                   for name in FIXED_NAMES]
    sigma_zeta = [poly_eval(c, zeta) for c in pk.sigma_coeffs]
    for v in (wire_evals + [m_eval, z_eval, z_next, phi_eval, phi_next]
              + uv_evals + t_evals + fixed_evals + sigma_zeta):
        tr.absorb_fr(v)
    v_ch = tr.challenge()
    tr.challenge()  # u: verifier-side cross-point fold; squeezed here only
    # to keep prover/verifier transcripts in lockstep

    openings = open_batch(
        params,
        [(zeta, wire_coeffs + [m_coeffs, z_coeffs, phi_coeffs] + uv_coeffs
          + chunks
          + [pk.fixed_coeffs[name] for name in FIXED_NAMES]
          + list(pk.sigma_coeffs)),
         (zeta * d.omega % R, [z_coeffs, phi_coeffs])],
        v_ch,
    )
    proof = Proof(wire_commits, m_commit, z_commit, phi_commit, uv_commits,
                  t_commits, wire_evals, m_eval, z_eval, z_next, phi_eval,
                  phi_next, uv_evals, t_evals, fixed_evals, sigma_zeta,
                  openings[0].witness, openings[1].witness)
    return proof.to_bytes()


def succinct_verify(pk: ProvingKey, public_inputs, proof_bytes: bytes,
                    transcript: str = "poseidon"):
    """The full verifier computation except the final pairing: returns
    the KZG accumulator (acc_l, acc_r), or None when any algebraic check
    fails. Needs no SRS — only the pairing decider does. This is the
    seam the aggregator (native and in-circuit) re-runs
    (snark-verifier's ``succinctly_verify`` shape,
    ``verifier/aggregator/native.rs:140-187``)."""
    try:
        proof = Proof.from_bytes(proof_bytes)
    except (ValueError, IndexError):
        return None
    d = pk.domain()
    n = d.n
    pubs = [int(v) % R for v in public_inputs]
    if len(pubs) != len(pk.public_rows):
        return None

    tr = make_transcript(transcript)
    for v in pubs:
        tr.absorb_fr(v)
    for cm in proof.wire_commits:
        tr.absorb_point(cm)
    tr.absorb_point(proof.m_commit)
    beta = tr.challenge()
    gamma = tr.challenge()
    beta_lk = tr.challenge()
    tr.absorb_point(proof.z_commit)
    tr.absorb_point(proof.phi_commit)
    for cm in proof.uv_commits:
        tr.absorb_point(cm)
    alpha = tr.challenge()
    for cm in proof.t_commits:
        tr.absorb_point(cm)
    zeta = tr.challenge()
    for v in (proof.wire_evals
              + [proof.m_eval, proof.z_eval, proof.z_next_eval,
                 proof.phi_eval, proof.phi_next_eval]
              + proof.uv_evals + proof.t_evals + proof.fixed_evals
              + proof.sigma_zeta):
        tr.absorb_fr(v)
    v_ch = tr.challenge()
    u_ch = tr.challenge()

    # fixed/σ evaluations come from the proof, bound to the vk
    # commitments through the batched opening below
    fixed = dict(zip(FIXED_NAMES, proof.fixed_evals))
    sigma = list(proof.sigma_zeta)
    zh = (pow(zeta, n, R) - 1) % R
    if zh == 0:
        return None
    pi = 0
    lag = d.lagrange_evals(zeta, pk.public_rows)
    for row, value in zip(pk.public_rows, pubs):
        pi = (pi - value * lag[row]) % R

    a, b, c, dd, e = proof.wire_evals[:5]
    gate = (
        fixed["q_a"] * a + fixed["q_b"] * b + fixed["q_c"] * c
        + fixed["q_d"] * dd + fixed["q_e"] * e
        + fixed["q_mul_ab"] * a * b + fixed["q_mul_cd"] * c * dd
        + fixed["q_const"] + pi
    ) % R
    fv = [(proof.wire_evals[w] + beta * pk.shifts[w] * zeta + gamma) % R
          for w in range(NUM_WIRES)]
    gv = [(proof.wire_evals[w] + beta * sigma[w] + gamma) % R
          for w in range(NUM_WIRES)]
    u1, u2, v1, v2 = proof.uv_evals
    link = (u2 * fv[4] % R * fv[5] - v2 * gv[4] % R * gv[5]) % R
    c_u1 = (u1 - proof.z_eval * fv[0] % R * fv[1]) % R
    c_u2 = (u2 - u1 * fv[2] % R * fv[3]) % R
    c_v1 = (v1 - proof.z_next_eval * gv[0] % R * gv[1]) % R
    c_v2 = (v2 - v1 * gv[2] % R * gv[3]) % R
    l0 = zh * pow(n * (zeta - 1) % R, -1, R) % R
    ba = (beta_lk + proof.wire_evals[LOOKUP_WIRE]) % R
    bt = (beta_lk + fixed["t_lookup"]) % R
    lk = ((proof.phi_next_eval - proof.phi_eval) * ba % R * bt
          - bt + proof.m_eval * ba) % R
    total = (
        gate
        + alpha * link
        + alpha * alpha % R * l0 * ((proof.z_eval - 1) % R)
        + pow(alpha, 3, R) * lk
        + pow(alpha, 4, R) * l0 * proof.phi_eval
        + pow(alpha, 5, R) * c_u1
        + pow(alpha, 6, R) * c_u2
        + pow(alpha, 7, R) * c_v1
        + pow(alpha, 8, R) * c_v2
    ) % R

    t_at_zeta = 0
    zn = pow(zeta, n, R)
    acc = 1
    for te in proof.t_evals:
        t_at_zeta = (t_at_zeta + te * acc) % R
        acc = acc * zn % R
    if total != zh * t_at_zeta % R:
        return None

    groups = [
        (zeta,
         [(cm, ev) for cm, ev in zip(proof.wire_commits, proof.wire_evals)]
         + [(proof.m_commit, proof.m_eval),
            (proof.z_commit, proof.z_eval),
            (proof.phi_commit, proof.phi_eval)]
         + [(cm, ev) for cm, ev in zip(proof.uv_commits, proof.uv_evals)]
         + [(cm, ev) for cm, ev in zip(proof.t_commits, proof.t_evals)]
         + list(zip(pk.commit_list(),
                    proof.fixed_evals + proof.sigma_zeta))),
        (zeta * d.omega % R,
         [(proof.z_commit, proof.z_next_eval),
          (proof.phi_commit, proof.phi_next_eval)]),
    ]
    from .kzg import BatchOpening

    openings = [BatchOpening(zeta, proof.w_x),
                BatchOpening(zeta * d.omega % R, proof.w_wx)]
    return fold_batch(groups, v_ch, u_ch, openings)


def verify(params: KZGParams, pk: ProvingKey, public_inputs,
           proof_bytes: bytes, transcript: str = "poseidon") -> bool:
    acc = succinct_verify(pk, public_inputs, proof_bytes,
                          transcript=transcript)
    if acc is None:
        return False
    return decide(params, *acc)
