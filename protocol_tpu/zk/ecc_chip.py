"""Elliptic-curve chips over wrong-field RNS integers.

Circuit twin of the reference's ``ecc`` module: affine point add /
double / windowed scalar-mul chipsets over 4×68-bit integers
(``eigentrust-zk/src/ecc/generic/mod.rs:140-1265``, window tables and
aux points per ``params/ecc/mod.rs:16-41``). Short-Weierstrass curves
y² = x³ + b only (secp256k1 and BN254 G1 both have a = 0).

Additions are incomplete (distinct-x), like the reference's, but the
λ-division here *hard-constrains* Δx ≠ 0 (witnessed inverse), so the
doubling degeneracy can never be used to leave λ unconstrained — a
colliding add makes the circuit unsatisfiable rather than unsound.
Scalar multiplication offsets every partial sum with nothing-up-my-
sleeve aux points (the reference's AuxInit/AuxFin pattern) so the
identity never appears on the add path; the aux mass is removed with
one final constant-point add.

Window digits come from ``IntegerChip.to_window_digits`` (4-bit,
lookup-constrained). Fixed-base tables are per-window constant points
(d·16^w·G + C), so their selects are pure linear combinations — no mul
rows at all on the fixed-base path.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.errors import EigenError
from ..utils.keccak import keccak256
from .gadgets import Cell, Chips
from .integer_chip import (
    B,
    NUM_LIMBS,
    TOTAL_BITS,
    AssignedInteger,
    IntegerChip,
)

WINDOW_BITS = 4
NUM_WINDOWS = TOTAL_BITS // WINDOW_BITS  # 68
TABLE_SIZE = 1 << WINDOW_BITS
# native-scalar path: 64 windows cover 256 bits ≥ the 254-bit field
NATIVE_WINDOWS = 256 // WINDOW_BITS


@dataclass
class CurveSpec:
    """Host-side curve oracle: exact ops on affine (x, y) int pairs, used
    for witness values and constant-point precomputation (never for
    constraints)."""

    p: int
    n: int
    b: int
    gen: tuple
    add: object  # (pt, pt) -> pt
    mul: object  # (pt, int) -> pt
    neg: object  # (pt) -> pt

    def aux_points(self, tag: str) -> tuple:
        """Two deterministic nothing-up-my-sleeve points (C, Aux)."""
        pts = []
        for name in (b"C", b"Aux"):
            seed = keccak256(b"protocol-tpu/ecc-aux/" + tag.encode() + b"/" + name)
            k = int.from_bytes(seed, "big") % self.n
            pts.append(self.mul(self.gen, k))
        return pts[0], pts[1]


def secp256k1_spec() -> CurveSpec:
    from ..crypto import secp256k1 as s

    def add(a, b):
        ra = s.AffinePoint(*a).add(s.AffinePoint(*b))
        return (ra.x, ra.y)

    def mul(a, k):
        ra = s.AffinePoint(*a).mul(k)
        return (ra.x, ra.y)

    def neg(a):
        return (a[0], s.P - a[1])

    return CurveSpec(p=s.P, n=s.N, b=7, gen=(s.GX, s.GY),
                     add=add, mul=mul, neg=neg)


@dataclass
class AssignedPoint:
    x: AssignedInteger
    y: AssignedInteger


class EccChip:
    """Point ops for one curve over an ``IntegerChip`` of its base field
    (EccAddChipset / EccDoubleChipset / EccMulChipset twins)."""

    def __init__(self, chips: Chips, fp: IntegerChip, spec: CurveSpec,
                 tag: str):
        if fp.p != spec.p:
            raise EigenError("circuit_error", "integer chip/base field mismatch")
        self.chips = chips
        self.fp = fp
        self.spec = spec
        self.aux_c, self.aux_init = spec.aux_points(tag)
        self._fixed_tables: dict = {}

    # --- assignment -------------------------------------------------------
    def assign_point(self, pt: tuple) -> AssignedPoint:
        x = self.fp.assign(pt[0])
        y = self.fp.assign(pt[1])
        p = AssignedPoint(x, y)
        self.assert_on_curve(p)
        return p

    def constant_point(self, pt: tuple) -> AssignedPoint:
        return AssignedPoint(self.fp.constant(pt[0]), self.fp.constant(pt[1]))

    def assert_on_curve(self, pt: AssignedPoint) -> None:
        """y·y ≡ x³ + b (mod p) in one CRT constraint."""
        fp = self.fp
        x2 = fp.square(pt.x)
        x3 = fp.mul(x2, pt.x)
        rhs = fp.add(x3, fp.constant(self.spec.b))
        fp.constrain_mul(pt.y, pt.y, rhs)

    # --- group ops --------------------------------------------------------
    def add(self, p1: AssignedPoint, p2: AssignedPoint) -> AssignedPoint:
        """Incomplete affine add; Δx ≠ 0 is hard-constrained."""
        fp = self.fp
        dx = fp.sub(p2.x, p1.x)
        dy = fp.sub(p2.y, p1.y)
        fp.assert_not_zero(dx)
        lam = fp.div(dy, dx)  # λ·Δx ≡ Δy
        lam_v = lam.value % fp.p
        x3_v = (lam_v * lam_v - p1.x.value - p2.x.value) % fp.p
        y3_v = (lam_v * (p1.x.value - x3_v) - p1.y.value) % fp.p
        x3 = fp.assign(x3_v)
        y3 = fp.assign(y3_v)
        # λ² ≡ x3 + x1 + x2
        fp.constrain_mul(lam, lam, fp.add(fp.add(x3, p1.x), p2.x))
        # λ·(x1 − x3) ≡ y3 + y1
        fp.constrain_mul(lam, fp.sub(p1.x, x3), fp.add(y3, p1.y))
        return AssignedPoint(x3, y3)

    def double(self, p1: AssignedPoint) -> AssignedPoint:
        """λ = 3x²/(2y); y = 0 makes the division unsatisfiable (no
        order-2 points on these curves anyway)."""
        fp = self.fp
        x2 = fp.square(p1.x)
        num = fp.mul_small(x2, 3)
        den = fp.mul_small(p1.y, 2)
        lam = fp.div(num, den)
        lam_v = lam.value % fp.p
        x3_v = (lam_v * lam_v - 2 * p1.x.value) % fp.p
        y3_v = (lam_v * (p1.x.value - x3_v) - p1.y.value) % fp.p
        x3 = fp.assign(x3_v)
        y3 = fp.assign(y3_v)
        fp.constrain_mul(lam, lam, fp.add(fp.add(x3, p1.x), p1.x))
        fp.constrain_mul(lam, fp.sub(p1.x, x3), fp.add(y3, p1.y))
        return AssignedPoint(x3, y3)

    # --- window select ----------------------------------------------------
    def _digit_flags(self, digit: Cell) -> list:
        c = self.chips
        eqs = [c.is_equal(digit, c.constant(d)) for d in range(TABLE_SIZE)]
        c.assert_equal(c.lincomb([(1, e) for e in eqs]), c.constant(1))
        return eqs

    def select_point(self, digit: Cell, table: list) -> AssignedPoint:
        """table[digit] for an in-circuit (witness) table."""
        c = self.chips
        fp = self.fp
        eqs = self._digit_flags(digit)
        dv = c.value(digit)
        coords = []
        for coord in ("x", "y"):
            limbs = []
            mx = []
            for i in range(NUM_LIMBS):
                cells = [getattr(pt, coord).limbs[i] for pt in table]
                prods = [c.mul(e, cell) for e, cell in zip(eqs, cells)]
                limbs.append(c.lincomb([(1, pr) for pr in prods]))
                mx.append(max(getattr(pt, coord).max_limb[i] for pt in table))
            value = getattr(table[dv], coord).value
            coords.append(AssignedInteger(limbs, value, mx))
        return AssignedPoint(*coords)

    def select_point_const(self, digit: Cell, host_table: list) -> AssignedPoint:
        """host_table[digit] for a constant table — selects are pure
        lincombs over the digit's one-hot flags."""
        c = self.chips
        eqs = self._digit_flags(digit)
        dv = c.value(digit)
        coords = []
        for axis in (0, 1):
            limbs = []
            mx = []
            for i in range(NUM_LIMBS):
                consts = [
                    (pt[axis] >> (68 * i)) & (B - 1) for pt in host_table
                ]
                limbs.append(
                    c.lincomb([(cv, e) for cv, e in zip(consts, eqs)]))
                mx.append(max(consts))
            coords.append(AssignedInteger(limbs, host_table[dv][axis], mx))
        return AssignedPoint(*coords)

    # --- scalar multiplication -------------------------------------------
    def scalar_mul(self, pt: AssignedPoint, digits: list) -> AssignedPoint:
        """Variable-base windowed mul (EccMulChipset twin). ``digits`` are
        LSB-first 4-bit cells of the scalar *representative* (scalar + k·n
        representatives are harmless: n·P = O)."""
        if len(digits) != NUM_WINDOWS:
            raise EigenError("circuit_error", "expected 68 window digits")
        # in-circuit table T[d] = d·P + C
        table = [self.constant_point(self.aux_c)]
        for _ in range(1, TABLE_SIZE):
            table.append(self.add(table[-1], pt))
        acc = self.constant_point(self.aux_init)
        for digit in reversed(digits):
            for _ in range(WINDOW_BITS):
                acc = self.double(acc)
            acc = self.add(acc, self.select_point(digit, table))
        # acc = 2^272·Aux + scalar·P + sC·C with sC = Σ 16^w
        s_c = ((1 << TOTAL_BITS) - 1) // (TABLE_SIZE - 1)
        mass = self.spec.add(
            self.spec.mul(self.aux_init, pow(2, TOTAL_BITS, self.spec.n)),
            self.spec.mul(self.aux_c, s_c % self.spec.n),
        )
        return self.add(acc, self.constant_point(self.spec.neg(mass)))

    def scalar_mul_fixed(self, digits: list,
                         base: tuple | None = None) -> AssignedPoint:
        """Fixed-base windowed mul of a constant point (default: the
        generator): constant per-window tables T_w[d] = (d·16^w)·base + C;
        68 adds, zero in-circuit doubles."""
        if len(digits) != NUM_WINDOWS:
            raise EigenError("circuit_error", "expected 68 window digits")
        tables = self._fixed_tables_for(base if base is not None
                                        else self.spec.gen)
        acc = self.constant_point(self.aux_init)
        for w, digit in enumerate(digits):
            acc = self.add(acc, self.select_point_const(digit, tables[w]))
        mass = self.spec.add(
            self.aux_init,
            self.spec.mul(self.aux_c, NUM_WINDOWS % self.spec.n),
        )
        return self.add(acc, self.constant_point(self.spec.neg(mass)))

    def _fixed_tables_for(self, base: tuple) -> list:
        key = base
        if key not in self._fixed_tables:
            tables = []
            for w in range(NUM_WINDOWS):
                window_base = self.spec.mul(
                    base, pow(TABLE_SIZE, w, self.spec.n))
                row = [self.aux_c]
                for d in range(1, TABLE_SIZE):
                    row.append(self.spec.add(row[-1], window_base))
                tables.append(row)
            self._fixed_tables[key] = tables
        return self._fixed_tables[key]

    # --- native-scalar path (same-curve chipset) --------------------------
    # Circuit twin of the reference's ``ecc/same_curve`` chipset
    # (eigentrust-zk/src/ecc/same_curve/mod.rs:134-1094 + native.rs):
    # when the curve's SCALAR field is the circuit's native field (bn254
    # G1 inside an Fr circuit — the in-circuit verifier's own folds),
    # the scalar needs no wrong-field RNS integer at all. The reference
    # Bits2Num's the native cell; here the cell decomposes into 64
    # lookup-constrained 4-bit windows, and a shared-doubling batched
    # MSM (its EccBatchedMulConfig counterpart) amortizes the 252
    # doublings across every point in a verifier fold.

    def native_digits(self, scalar: Cell) -> list:
        """64 LSB-first 4-bit digit cells of a NATIVE scalar cell.

        The recomposition constraint binds Σ dᵤ·16ᵘ ≡ scalar (mod r)
        only, so a malicious witness may encode scalar + k·r (k ≤ 5,
        still < 2^256). That freedom is harmless exactly here: r is the
        order of the curve's scalar group, so (s + k·r)·P = s·P — the
        same argument that lets the reference feed raw Bits2Num output
        to its same-curve mul (same_curve/mod.rs:134)."""
        c = self.chips
        v = c.value(scalar)
        digits = []
        terms = []
        for w in range(NATIVE_WINDOWS):
            dv = (v >> (WINDOW_BITS * w)) & (TABLE_SIZE - 1)
            d = c.assign_range(dv, WINDOW_BITS)
            digits.append(d)
            terms.append((1 << (WINDOW_BITS * w), d))
        c.assert_equal(c.lincomb(terms), scalar)
        return digits

    def msm_native(self, items: list) -> AssignedPoint:
        """Batched MSM Σ sᵢ·Pᵢ with ONE shared doubling chain.

        ``items``: (point, digits) pairs — point an ``AssignedPoint``
        (in-circuit 16-entry table, 15 adds) or a host (x, y) tuple
        (constant table, selects are pure lincombs); digits from
        :meth:`native_digits`. Every point rides the same 252 doubles
        (the per-point scalar_mul pays them each), which is where the
        verifier-fold row count collapses. Aux offsets keep the
        incomplete adds away from the identity; the aggregate aux mass
        2^252·Aux + K·(Σ16ᵘ)·C leaves with one constant-point add."""
        return self.msm_digits(items, NATIVE_WINDOWS)

    def msm_digits(self, items: list, num_windows: int) -> AssignedPoint:
        """Shared-doubling windowed MSM over 4-bit digit-cell scalars of
        ``num_windows`` LSB-first windows (the :meth:`msm_native` core,
        window count lifted so the EcdsaChip's GLV half-scalars — 33
        windows for |s| < 2^129 — ride the same loop)."""
        if not items:
            raise EigenError("circuit_error", "msm needs items")
        tables = []
        for pt, digits in items:
            if len(digits) != num_windows:
                raise EigenError(
                    "circuit_error",
                    f"expected {num_windows} window digits")
            if isinstance(pt, AssignedPoint):
                tbl = [self.constant_point(self.aux_c)]
                for _ in range(1, TABLE_SIZE):
                    tbl.append(self.add(tbl[-1], pt))
                tables.append((True, tbl))
            else:
                row = [self.aux_c]
                for _ in range(1, TABLE_SIZE):
                    row.append(self.spec.add(row[-1], pt))
                tables.append((False, row))
        acc = self.constant_point(self.aux_init)
        for w in reversed(range(num_windows)):
            if w != num_windows - 1:
                for _ in range(WINDOW_BITS):
                    acc = self.double(acc)
            for (in_circuit, tbl), (pt, digits) in zip(tables, items):
                sel = (self.select_point(digits[w], tbl) if in_circuit
                       else self.select_point_const(digits[w], tbl))
                acc = self.add(acc, sel)
        s_c = ((1 << (WINDOW_BITS * num_windows)) - 1) // (TABLE_SIZE - 1)
        mass = self.spec.add(
            self.spec.mul(self.aux_init,
                          pow(2, WINDOW_BITS * (num_windows - 1),
                              self.spec.n)),
            self.spec.mul(self.aux_c, len(items) * s_c % self.spec.n),
        )
        return self.add(acc, self.constant_point(self.spec.neg(mass)))

    def scalar_mul_native(self, pt: AssignedPoint, scalar: Cell
                          ) -> AssignedPoint:
        """Single variable-base mul by a native scalar cell."""
        return self.msm_native([(pt, self.native_digits(scalar))])

    def scalar_mul_fixed_native(self, digits: list,
                                base: tuple | None = None) -> AssignedPoint:
        """Fixed-base mul by native digits: 64 constant per-window
        tables T_w[d] = (d·16ʷ)·base + C — zero in-circuit doubles."""
        if len(digits) != NATIVE_WINDOWS:
            raise EigenError("circuit_error",
                             "expected 64 native window digits")
        base = base if base is not None else self.spec.gen
        # the native windows are exactly the first 64 of the generic 68
        tables = self._fixed_tables_for(base)[:NATIVE_WINDOWS]
        acc = self.constant_point(self.aux_init)
        for w, digit in enumerate(digits):
            acc = self.add(acc, self.select_point_const(digit, tables[w]))
        mass = self.spec.add(
            self.aux_init,
            self.spec.mul(self.aux_c, NATIVE_WINDOWS % self.spec.n),
        )
        return self.add(acc, self.constant_point(self.spec.neg(mass)))
