"""Threshold circuit: prove "peer X's score ≥ T" against aggregated
EigenTrust public inputs.

Circuit twin of the reference's ``Threshold`` halo2 circuit
(``eigentrust-zk/src/circuits/threshold/mod.rs:284-632``) over the
native twin ``protocol_tpu.models.threshold``:

- the EigenTrust snark is aggregated (mod.rs:401-431): its public
  inputs become cells of this circuit, and the KZG accumulator limbs
  are exposed as public inputs for the deferred pairing decider —
  either re-derived fully in-circuit (``aggregate=True``, the
  AggregatorChipset twin) or bound as witnesses for the native
  aggregator's output,
- target peer's score selected with set-position/select-item chips
  (mod.rs:433-445),
- decimal limbs range-checked (mod.rs:474-516; the reference uses
  252-bit LessEqual chips, here lookup-backed comparisons),
- num/den recomposed and num·den⁻¹ == score constrained
  (mod.rs:518-578),
- threshold comparison on the most-significant limbs with the result
  bit constrained to a public input (mod.rs:580-631).

Public input layout matches ``ThPublicInputs``
(``eigentrust/src/circuit.rs:153-236``):
target_address ‖ threshold ‖ th_check ‖ accumulator limbs (16).
"""

from __future__ import annotations

from fractions import Fraction

from ..models.threshold import Threshold
from ..utils.errors import EigenError
from ..utils.fields import BN254_FR_MODULUS, Fr
from .gadgets import Chips
from .plonk import ConstraintSystem

R = BN254_FR_MODULUS

DEFAULT_LOOKUP_BITS = 17


class ThresholdCircuit:
    """Builder for the Threshold4 shape (``circuits/mod.rs:146-157``)."""

    def __init__(self, num_neighbours: int = 4, num_limbs: int = 2,
                 power_of_ten: int = 72, initial_score: int = 1000,
                 lookup_bits: int = DEFAULT_LOOKUP_BITS):
        self.n = num_neighbours
        self.num_limbs = num_limbs
        self.power_of_ten = power_of_ten
        self.initial_score = initial_score
        self.lookup_bits = lookup_bits
        if 10 ** power_of_ten >= 1 << 250:
            raise EigenError("circuit_error", "decimal limb exceeds compare width")

    def build(self, et_instances: list, target_address: Fr, threshold: Fr,
              ratio: Fraction, aggregator_limbs: list,
              chips: Chips | None = None, et_cells: list | None = None):
        """Returns (chips, public_inputs).

        ``et_instances``: the EigenTrust circuit's public inputs
        (participants ‖ scores ‖ domain ‖ opinions_hash). When an
        AggregatorChipset has already assigned them, pass its cells via
        ``et_cells`` (and its accumulator cells as ``aggregator_limbs``)
        — that is the sound path (``build_aggregated``). Without
        ``et_cells`` the instances enter as FREE witnesses: nothing
        links them to the accumulator limbs, so the result is only
        meaningful for MockProver-style structural testing, never for
        proofs shown to a third party.
        """
        n = self.n
        native = Threshold(
            score=Fr(et_instances[n + self._target_index(et_instances,
                                                         target_address)]),
            ratio=ratio, threshold=threshold, num_limbs=self.num_limbs,
            power_of_ten=self.power_of_ten, num_neighbours=n,
            initial_score=self.initial_score)

        c = chips if chips is not None else Chips(
            ConstraintSystem(lookup_bits=self.lookup_bits))
        if et_cells is None:
            et_cells = [c.witness(int(v)) for v in et_instances]
        participants = et_cells[:n]
        scores = et_cells[n : 2 * n]

        target_cell = c.witness(int(target_address))
        threshold_cell = c.witness(int(threshold))

        # --- select the target's score (mod.rs:433-445) -------------------
        pos = c.set_position(target_cell, participants)
        score = c.select_item(pos, scores)

        # --- decimal limbs (mod.rs:474-516) -------------------------------
        base = 10 ** self.power_of_ten
        limb_bits = (base - 1).bit_length() + 1
        num_limbs = [c.witness(int(v)) for v in native.num_decomposed]
        den_limbs = [c.witness(int(v)) for v in native.den_decomposed]
        base_cell = c.constant(base)
        for limb in (*num_limbs, *den_limbs):
            c.range_check(limb, limb_bits)
            c.assert_equal(c.less_than(limb, base_cell, num_bits=limb_bits),
                           c.constant(1))

        # --- recompose and bind to the field score (mod.rs:518-578) -------
        composed_num = c.lincomb(
            [(pow(base, i, R), limb) for i, limb in enumerate(num_limbs)])
        composed_den = c.lincomb(
            [(pow(base, i, R), limb) for i, limb in enumerate(den_limbs)])
        # score·den == num  (den ≠ 0 enforced by the last-limb check below)
        c.assert_equal(c.mul(score, composed_den), composed_num)

        # --- threshold compare on the top limbs (mod.rs:580-631) ----------
        max_score = self.n * self.initial_score
        c.assert_equal(
            c.less_than(threshold_cell, c.constant(max_score),
                        num_bits=max_score.bit_length() + 1),
            c.constant(1))
        last_num = num_limbs[-1]
        last_den = den_limbs[-1]
        # last_den != 0 (native asserts; here via inverse existence)
        c.inverse(last_den)
        comp = c.mul(last_den, threshold_cell)
        c.range_check(comp, 252)
        th_bit = c.less_eq(comp, last_num, num_bits=252)
        if bool(c.value(th_bit)) != native.check_threshold():
            raise EigenError("circuit_error",
                             "circuit/native threshold verdict divergence")

        # --- public inputs: addr ‖ threshold ‖ bit ‖ accumulator ----------
        c.public(target_cell)
        c.public(threshold_cell)
        c.public(th_bit)
        for limb in aggregator_limbs:
            if hasattr(limb, "wire"):
                c.public(limb)
            else:
                c.public(c.witness(int(limb)))
        return c, c.cs.public_values()

    def build_aggregated(self, et_pk, et_instances: list, et_proof: bytes,
                         target_address: Fr, threshold: Fr,
                         ratio: Fraction):
        """The reference's full Threshold shape (mod.rs:284-632): the ET
        snark is verified in-circuit by the AggregatorChipset; its public
        inputs become this circuit's cells and the derived accumulator
        limbs become public inputs for the host decider."""
        from .loader_chip import AggregatorChipset

        chips = Chips(ConstraintSystem(lookup_bits=self.lookup_bits))
        et_cells = [chips.witness(int(v)) for v in et_instances]
        agg = AggregatorChipset(chips)
        limb_cells, _ = agg.aggregate([(et_pk, et_cells, et_proof)])
        return self.build(et_instances, target_address, threshold, ratio,
                          limb_cells, chips=chips, et_cells=et_cells)

    def _target_index(self, et_instances, target_address: Fr) -> int:
        for i in range(self.n):
            if int(et_instances[i]) == int(target_address):
                return i
        raise EigenError("circuit_error", "target not among participants")
