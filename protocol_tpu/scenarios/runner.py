"""Reproducible scenario driver — one seeded {topology × semiring} run.

This is the single engine behind the ``scenario`` CLI verb,
``bench.py --scenario`` and the serve smoke's scenario phase: build a
seeded adversarial topology, converge it through the ConvergeBackend
seam under the requested semiring, converge the attack-free baseline
(same graph with every attacker-incident edge dropped), and score the
outcome with :mod:`.metrics`.

The default report is **byte-identical across runs of the same seed on
the same box**: every field is a pure function of (topology params,
seed, semiring, solver knobs). Wall-clock timing is opt-in
(``timing=True``) and lands in a separate key the CLI excludes by
default, precisely so ``scenario run ... --seed 7`` twice diffs clean.
"""

from __future__ import annotations

import inspect
import time

import numpy as np

from ..utils import trace
from .metrics import robustness_report
from .topologies import TOPOLOGIES, build_topology

SCENARIO_SCHEMA = "ptpu-scenario-v1"

# Above this edge count the gather-SpMV working set outgrows the sparse
# path's sweet spot and the Clos-routed operator (one-time plan build,
# then streaming-bandwidth sweeps) wins; below it the routed plan build
# dominates a one-shot scenario run.
ROUTED_EDGE_THRESHOLD = 20_000_000


def list_scenarios() -> list[dict]:
    """Catalog of topologies: name, one-line description, tunable knobs
    with their defaults (everything ``scenario run`` accepts)."""
    out = []
    for name, builder in sorted(TOPOLOGIES.items()):
        sig = inspect.signature(builder)
        doc = (builder.__doc__ or "").strip().splitlines()[0]
        out.append({
            "topology": name,
            "description": doc,
            "defaults": {p.name: p.default for p in sig.parameters.values()},
        })
    return out


def _resolve_engine(engine: str, n_edges: int) -> str:
    if engine == "auto":
        return "routed" if n_edges >= ROUTED_EDGE_THRESHOLD else "sparse"
    if engine not in ("sparse", "routed"):
        raise ValueError(f"unknown engine {engine!r} "
                         "(have: auto, sparse, routed)")
    return engine


def _make_backend(engine: str):
    from ..backend import JaxRoutedBackend, JaxSparseBackend

    return JaxRoutedBackend() if engine == "routed" else JaxSparseBackend()


def run_scenario(topology: str, peers: int = 10_000,
                 attacker_fraction: float = 0.1, semiring=None,
                 seed: int = 0, alpha: float = 0.1, tol: float = 1e-6,
                 max_iterations: int = 100, engine: str = "auto",
                 baseline: bool = True, timing: bool = False,
                 initial_score: float = 1000.0, **topology_kwargs) -> dict:
    """Run one adversarial scenario end to end and return the report.

    ``baseline=True`` additionally converges the attack-free control —
    the same edge list with every attacker-incident edge removed — so
    the robustness block can measure rank displacement and captured
    mass against what the honest graph alone would have produced.
    Topologies with no attackers (``smallworld``) are their own
    baseline and skip the second converge.
    """
    from ..ops.converge import resolve_semiring

    sr = resolve_semiring(semiring)
    build_kwargs = dict(peers=peers, seed=seed, **topology_kwargs)
    if topology != "smallworld":
        build_kwargs["attacker_fraction"] = attacker_fraction
    t_build = time.perf_counter()
    graph = build_topology(topology, **build_kwargs)
    build_s = time.perf_counter() - t_build

    n_edges = len(graph.src)
    eng = _resolve_engine(engine, n_edges)
    backend = _make_backend(eng)
    valid = np.ones(graph.n, dtype=bool)

    trace.counter("scenario_runs").inc(topology=topology)
    with trace.span("scenario.run", topology=topology, semiring=sr.name,
                    peers=graph.n, edges=n_edges, engine=eng):
        t_run = time.perf_counter()
        scores, iters, delta = backend.converge_edges(
            graph.n, graph.src, graph.dst, graph.val, valid,
            initial_score, max_iterations, tol=tol, alpha=alpha,
            semiring=sr)
        attack_s = time.perf_counter() - t_run

        t_base = time.perf_counter()
        if baseline and graph.n_attackers:
            keep = ~(graph.attacker[graph.src] | graph.attacker[graph.dst])
            base_scores, base_iters, _ = backend.converge_edges(
                graph.n, graph.src[keep], graph.dst[keep],
                graph.val[keep], valid, initial_score, max_iterations,
                tol=tol, alpha=alpha, semiring=sr)
        else:
            base_scores, base_iters = scores, iters
        baseline_s = time.perf_counter() - t_base

    report = {
        "schema": SCENARIO_SCHEMA,
        "topology": topology,
        "peers": int(graph.n),
        "edges": int(n_edges),
        "attackers": int(graph.n_attackers),
        "semiring": sr.name,
        "seed": int(seed),
        "alpha": float(alpha),
        "tol": float(tol),
        "max_iterations": int(max_iterations),
        "engine": eng,
        "params": {k: v for k, v in graph.params.items()},
        "scores": {
            "iterations": int(iters),
            "residual": float(delta),
            "baseline_iterations": int(base_iters),
        },
        "robustness": robustness_report(
            scores, base_scores, graph.attacker, int(iters),
            alpha, tol),
    }
    if timing:
        report["timing_s"] = {
            "build": build_s,
            "attack_converge": attack_s,
            "baseline_converge": baseline_s,
        }
    return report
