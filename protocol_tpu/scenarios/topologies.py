"""Adversarial trust-graph generators — deterministic, seeded, vectorized.

Every builder returns a :class:`ScenarioGraph`: raw attestation edge
arrays (the exact shape ``graph.filter_edges`` / the backends consume)
plus the ground-truth attacker mask the robustness metrics score
against. Layout convention: honest peers occupy ids ``[0, n_honest)``,
attackers ``[n_honest, n)`` — the mask is the contract, not the id
split, so metrics never assume it.

All randomness flows through one ``np.random.default_rng(seed)`` per
build and every edge family is emitted by whole-array ops (no Python
per-edge loops), so a 10M-peer graph builds in seconds and the same
seed reproduces the same arrays byte-for-byte on any box.

The attack families are the classic EigenTrust threat models:

- **sybil ring**: attackers attest each other in a cycle at maximum
  value, funneling extra weight into one front sybil; a small fooled
  fraction of honest peers attests the front (the bridge mass every
  sybil analysis shows is the attack's real budget).
- **collusion cluster**: attackers form dense mutual-attestation
  cliques and camouflage with low-value attestations toward random
  honest peers, plus the same fooled-bridge in-mass.
- **slander campaign**: attackers rate many honest peers at the
  maximum value but the victim set at the minimum — under row
  normalization the victims' share of every attacker row collapses,
  displacing their rank without a single forged positive edge.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class ScenarioGraph:
    """One generated scenario: raw edges + ground-truth attacker mask."""

    name: str
    n: int
    src: np.ndarray        # int64 attester ids
    dst: np.ndarray        # int64 subject ids
    val: np.ndarray        # float64 attestation values (> 0)
    attacker: np.ndarray   # bool [n] — ground truth for the metrics
    params: dict = field(default_factory=dict)

    @property
    def n_attackers(self) -> int:
        return int(self.attacker.sum())


def _smallworld_edges(n: int, k: int, rewire: float,
                      rng: np.random.Generator, low: int, high: int):
    """Watts–Strogatz-style directed small world over ids [0, n): ring
    lattice (each peer attests its k nearest neighbors, both sides) with
    a ``rewire`` fraction of targets re-pointed uniformly. Vectorized:
    one (n, k) offset grid, one rewire mask draw."""
    half = max(1, k // 2)
    offs = np.concatenate([np.arange(1, half + 1),
                           -np.arange(1, half + 1)])
    src = np.repeat(np.arange(n, dtype=np.int64), len(offs))
    dst = (src + np.tile(offs, n)) % n
    moved = rng.random(len(dst)) < rewire
    dst = np.where(moved, rng.integers(0, n, len(dst)), dst)
    val = rng.integers(low, high + 1, len(src)).astype(np.float64)
    return src, dst, val


def honest_smallworld(peers: int = 10_000, seed: int = 0, k: int = 8,
                      rewire: float = 0.1, low: int = 1,
                      high: int = 10) -> ScenarioGraph:
    """The attack-free control: every peer is honest. The baseline the
    robustness metrics rank-compare against uses exactly this shape."""
    if peers < 4:
        raise ValueError("smallworld needs >= 4 peers")
    rng = np.random.default_rng(seed)
    src, dst, val = _smallworld_edges(peers, k, rewire, rng, low, high)
    return ScenarioGraph(
        name="smallworld", n=peers, src=src, dst=dst, val=val,
        attacker=np.zeros(peers, dtype=bool),
        params={"peers": peers, "seed": seed, "k": k, "rewire": rewire,
                "low": low, "high": high})


def _split(peers: int, attacker_fraction: float):
    n_att = int(round(peers * attacker_fraction))
    n_att = min(max(n_att, 1), peers - 2)
    return peers - n_att, n_att


def _bridges(rng, n_honest: int, n_att: int, fooled_fraction: float,
             front: np.ndarray, high: int):
    """The fooled-honest in-mass every attack needs: a seeded sample of
    honest peers attests attacker entry points at full value."""
    n_fooled = max(1, int(round(n_honest * fooled_fraction)))
    fooled = rng.choice(n_honest, size=min(n_fooled, n_honest),
                        replace=False).astype(np.int64)
    b_dst = front[rng.integers(0, len(front), len(fooled))]
    b_val = np.full(len(fooled), float(high))
    return fooled, b_dst, b_val


def sybil_ring(peers: int = 10_000, attacker_fraction: float = 0.1,
               seed: int = 0, k: int = 8, rewire: float = 0.1,
               fooled_fraction: float = 0.01, low: int = 1,
               high: int = 10) -> ScenarioGraph:
    """Sybil ring: attackers cycle maximum-value attestations and every
    sybil additionally endorses the ring's front node."""
    n_honest, n_att = _split(peers, attacker_fraction)
    rng = np.random.default_rng(seed)
    h_src, h_dst, h_val = _smallworld_edges(n_honest, k, rewire, rng,
                                            low, high)
    att = np.arange(n_honest, peers, dtype=np.int64)
    front = att[:1]
    ring_src = att
    ring_dst = np.roll(att, -1)
    ring_val = np.full(n_att, float(high))
    # the funnel: every sybil (front included — a self-edge the filter
    # drops) also endorses the front at max value
    fun_src = att
    fun_dst = np.full(n_att, front[0], dtype=np.int64)
    fun_val = np.full(n_att, float(high))
    fooled, b_dst, b_val = _bridges(rng, n_honest, n_att,
                                    fooled_fraction, front, high)
    src = np.concatenate([h_src, ring_src, fun_src, fooled])
    dst = np.concatenate([h_dst, ring_dst, fun_dst, b_dst])
    val = np.concatenate([h_val, ring_val, fun_val, b_val])
    attacker = np.zeros(peers, dtype=bool)
    attacker[n_honest:] = True
    return ScenarioGraph(
        name="sybil-ring", n=peers, src=src, dst=dst, val=val,
        attacker=attacker,
        params={"peers": peers, "attacker_fraction": attacker_fraction,
                "seed": seed, "k": k, "rewire": rewire,
                "fooled_fraction": fooled_fraction, "low": low,
                "high": high})


def collusion_cluster(peers: int = 10_000, attacker_fraction: float = 0.1,
                      seed: int = 0, k: int = 8, rewire: float = 0.1,
                      cluster_size: int = 16, camouflage: int = 2,
                      fooled_fraction: float = 0.01, low: int = 1,
                      high: int = 10) -> ScenarioGraph:
    """Collusion clusters: attackers in cliques of ``cluster_size``
    cross-attest at max value and camouflage with ``camouflage``
    low-value attestations toward random honest peers each."""
    n_honest, n_att = _split(peers, attacker_fraction)
    rng = np.random.default_rng(seed)
    h_src, h_dst, h_val = _smallworld_edges(n_honest, k, rewire, rng,
                                            low, high)
    att = np.arange(n_honest, peers, dtype=np.int64)
    csize = max(2, min(cluster_size, n_att))
    cluster_of = (att - n_honest) // csize
    # intra-cluster: each member attests min(csize-1, 4) random
    # fellow members (offset 1..csize-1 within the cluster, mod its
    # true size — vectorized, self-edges impossible)
    fan = min(csize - 1, 4)
    c_src = np.repeat(att, fan)
    base = np.repeat(cluster_of * csize, fan)
    within = np.repeat(att - n_honest - cluster_of * csize, fan)
    cl_n = np.repeat(np.minimum((cluster_of + 1) * csize, n_att)
                     - cluster_of * csize, fan)
    step = rng.integers(1, np.maximum(cl_n, 2))
    c_dst = n_honest + base + (within + step) % cl_n
    c_val = np.full(len(c_src), float(high))
    # camouflage: low-value attestations toward random honest peers
    cam_src = np.repeat(att, camouflage)
    cam_dst = rng.integers(0, n_honest, len(cam_src)).astype(np.int64)
    cam_val = np.full(len(cam_src), float(low))
    fronts = att[cluster_of * csize == att - n_honest]  # cluster heads
    fooled, b_dst, b_val = _bridges(rng, n_honest, n_att,
                                    fooled_fraction, fronts, high)
    src = np.concatenate([h_src, c_src, cam_src, fooled])
    dst = np.concatenate([h_dst, c_dst, cam_dst, b_dst])
    val = np.concatenate([h_val, c_val, cam_val, b_val])
    attacker = np.zeros(peers, dtype=bool)
    attacker[n_honest:] = True
    return ScenarioGraph(
        name="collusion", n=peers, src=src, dst=dst, val=val,
        attacker=attacker,
        params={"peers": peers, "attacker_fraction": attacker_fraction,
                "seed": seed, "k": k, "rewire": rewire,
                "cluster_size": cluster_size, "camouflage": camouflage,
                "fooled_fraction": fooled_fraction, "low": low,
                "high": high})


def slander_campaign(peers: int = 10_000, attacker_fraction: float = 0.1,
                     seed: int = 0, k: int = 8, rewire: float = 0.1,
                     victim_fraction: float = 0.05, spread: int = 8,
                     fooled_fraction: float = 0.01, low: int = 1,
                     high: int = 10) -> ScenarioGraph:
    """Slander/badmouthing: each attacker rates ``spread`` random
    honest peers at max value and one victim at the minimum — row
    normalization then collapses the victims' share of attacker mass.
    Victims are the first ``victim_fraction`` of honest ids (the
    metrics read them from ``params["victims"]``)."""
    n_honest, n_att = _split(peers, attacker_fraction)
    rng = np.random.default_rng(seed)
    h_src, h_dst, h_val = _smallworld_edges(n_honest, k, rewire, rng,
                                            low, high)
    att = np.arange(n_honest, peers, dtype=np.int64)
    n_victims = max(1, int(round(n_honest * victim_fraction)))
    # boost edges: max value toward random NON-victim honest peers
    s_src = np.repeat(att, spread)
    s_dst = rng.integers(n_victims, n_honest, len(s_src)).astype(np.int64)
    s_val = np.full(len(s_src), float(high))
    # the slander itself: minimum value toward a victim each
    v_src = att
    v_dst = rng.integers(0, n_victims, n_att).astype(np.int64)
    v_val = np.full(n_att, float(low))
    fooled, b_dst, b_val = _bridges(rng, n_honest, n_att,
                                    fooled_fraction, att[:1], high)
    src = np.concatenate([h_src, s_src, v_src, fooled])
    dst = np.concatenate([h_dst, s_dst, v_dst, b_dst])
    val = np.concatenate([h_val, s_val, v_val, b_val])
    attacker = np.zeros(peers, dtype=bool)
    attacker[n_honest:] = True
    return ScenarioGraph(
        name="slander", n=peers, src=src, dst=dst, val=val,
        attacker=attacker,
        params={"peers": peers, "attacker_fraction": attacker_fraction,
                "seed": seed, "k": k, "rewire": rewire,
                "victim_fraction": victim_fraction, "spread": spread,
                "fooled_fraction": fooled_fraction, "low": low,
                "high": high, "victims": n_victims})


TOPOLOGIES = {
    "smallworld": honest_smallworld,
    "sybil-ring": sybil_ring,
    "collusion": collusion_cluster,
    "slander": slander_campaign,
}


def build_topology(name: str, **kwargs) -> ScenarioGraph:
    """Build a named topology; unknown names raise with the catalog."""
    try:
        builder = TOPOLOGIES[name]
    except KeyError:
        raise ValueError(f"unknown topology {name!r} (have: "
                         f"{sorted(TOPOLOGIES)})") from None
    return builder(**kwargs)
