"""Adversarial scenario harness over the generalized-semiring converge.

The system computes trust scores; this package attacks them. Three
layers:

- :mod:`topologies` — deterministic, seeded, fully vectorized edge-array
  builders for the canonical EigenTrust attack families (sybil rings,
  collusion clusters, slander campaigns) over an honest small-world
  baseline, parameterized by attacker fraction and scale (designed to
  10M peers);
- :mod:`metrics` — robustness outcomes: attacker score-mass capture,
  honest-peer rank displacement vs the attack-free baseline, measured
  iteration counts vs the damped-convergence-bound prediction;
- :mod:`runner` — the reproducible driver behind the ``scenario`` CLI
  verb, ``bench.py --scenario`` and the serve smoke's scenario phase:
  one seeded run of {topology x semiring} through the ConvergeBackend
  seam, emitting a deterministic JSON report (byte-identical across
  runs of the same seed — wall-clock timing is opt-in, never default).
"""

from .metrics import (
    attacker_mass_capture,
    iteration_bound,
    rank_displacement,
    robustness_report,
)
from .runner import SCENARIO_SCHEMA, list_scenarios, run_scenario
from .topologies import (
    ScenarioGraph,
    TOPOLOGIES,
    build_topology,
    collusion_cluster,
    honest_smallworld,
    slander_campaign,
    sybil_ring,
)

__all__ = [
    "ScenarioGraph",
    "TOPOLOGIES",
    "SCENARIO_SCHEMA",
    "attacker_mass_capture",
    "build_topology",
    "collusion_cluster",
    "honest_smallworld",
    "iteration_bound",
    "list_scenarios",
    "rank_displacement",
    "robustness_report",
    "run_scenario",
    "slander_campaign",
    "sybil_ring",
]
