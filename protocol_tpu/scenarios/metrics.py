"""Robustness outcomes of one adversarial scenario run.

All metrics are pure numpy over (scores, attacker mask) — no backend
or device dependency, so the same functions score a live daemon's
served table (the smoke's scenario phase) and a batch run (the CLI /
bench drivers).
"""

from __future__ import annotations

import math

import numpy as np


def attacker_mass_capture(scores, attacker) -> float:
    """Fraction of the total score mass held by attacker peers — the
    headline sybil-resistance number (0 = none captured)."""
    scores = np.asarray(scores, dtype=np.float64)
    attacker = np.asarray(attacker, dtype=bool)
    total = float(scores.sum())
    if total <= 0.0:
        return 0.0
    return float(scores[attacker].sum()) / total


def rank_displacement(baseline_scores, scores, honest) -> dict:
    """How far the attack moved honest peers in the ranking.

    Both vectors are ranked descending (stable: ties break by peer id,
    so the metric is deterministic), then compared ONLY on the honest
    peers, by their rank among honest peers — attacker rows squeezing
    into the global order is what `attacker_mass_capture` measures;
    this isolates the reordering damage among the honest population.
    Returns mean/max absolute displacement and the fraction of honest
    peers displaced at all."""
    honest = np.asarray(honest, dtype=bool)
    b = np.asarray(baseline_scores, dtype=np.float64)
    a = np.asarray(scores, dtype=np.float64)
    if b.shape != a.shape or b.shape != honest.shape:
        raise ValueError("baseline/attack score vectors disagree on "
                         "the honest population")
    b, a = b[honest], a[honest]
    # rank of each honest peer = position in the stable descending sort
    def ranks(v):
        order = np.argsort(-v, kind="stable")
        r = np.empty(len(v), dtype=np.int64)
        r[order] = np.arange(len(v))
        return r

    shift = np.abs(ranks(a) - ranks(b))
    return {
        "mean": float(shift.mean()) if len(shift) else 0.0,
        "max": int(shift.max()) if len(shift) else 0,
        "moved_fraction": float((shift > 0).mean()) if len(shift) else 0.0,
    }


def attackers_in_top(scores, attacker, top: int = 100) -> int:
    """Attacker peers inside the global top-``top`` ranks (stable
    descending order) — the 'did a sybil reach the leaderboard'
    check."""
    scores = np.asarray(scores, dtype=np.float64)
    attacker = np.asarray(attacker, dtype=bool)
    order = np.argsort(-scores, kind="stable")[:min(top, len(scores))]
    return int(attacker[order].sum())


def iteration_bound(alpha: float, tol: float) -> int | None:
    """Predicted adaptive-iteration count from the damped-convergence
    bound: with pre-trust mixing ``alpha``, the iteration contracts
    geometrically at rate (1 - alpha), so the relative-L1 stop at
    ``tol`` is reached within ``ceil(ln tol / ln(1 - alpha))`` sweeps
    regardless of graph spectrum. ``alpha == 0`` has no spectrum-free
    bound — returns None (the report then records the measured count
    uncompared)."""
    if alpha <= 0.0 or alpha >= 1.0 or tol <= 0.0 or tol >= 1.0:
        return None
    return int(math.ceil(math.log(tol) / math.log(1.0 - alpha)))


def robustness_report(scores, baseline_scores, attacker,
                      iterations: int, alpha: float, tol: float,
                      top: int = 100) -> dict:
    """The full robustness block of one scenario run (deterministic:
    pure functions of the inputs)."""
    attacker = np.asarray(attacker, dtype=bool)
    bound = iteration_bound(alpha, tol)
    return {
        "attacker_mass_capture": attacker_mass_capture(scores, attacker),
        "baseline_attacker_mass": attacker_mass_capture(baseline_scores,
                                                        attacker),
        "honest_rank_displacement": rank_displacement(
            baseline_scores, scores, ~attacker),
        "attackers_in_top": {"top": top,
                             "count": attackers_in_top(scores, attacker,
                                                       top)},
        "iterations": int(iterations),
        "iteration_bound": bound,
        "within_bound": (None if bound is None
                         else bool(int(iterations) <= bound)),
    }
