"""protocol_tpu — a TPU-native trust-graph framework.

A ground-up re-design of the capabilities of kumavis/protocol ("ZK Eigen
Trust", Rust + halo2): signed-attestation ingestion, byzantine-robust opinion
filtering, EigenTrust global-trust convergence, threshold checks, and a
ZK-circuit layer — with the convergence computation lifted onto TPU via
JAX/XLA/Pallas behind a ``ConvergeBackend`` seam.

Package layout (mirrors SURVEY.md §7 architecture):

- ``utils``    — prime fields, keccak, errors (host-exact building blocks)
- ``crypto``   — native crypto oracles: Poseidon, Rescue-Prime, secp256k1
                 ECDSA, BabyJubJub EdDSA, Merkle trees
- ``models``   — the EigenTrust set/opinion/threshold semantics
                 (reference: eigentrust-zk/src/circuits/{dynamic_sets,opinion,
                 threshold}/native.rs)
- ``ops``      — TPU compute: dense/sparse converge kernels, batched field
                 ops, batched Poseidon / ECDSA
- ``parallel`` — device-mesh sharding: row-sharded SpMV power iteration with
                 ICI collectives (shard_map + psum/all_gather)
- ``client``   — the SDK facade: attestation codecs, storage, eth utils,
                 chain ingestion (reference: eigentrust/src/*)
- ``cli``      — command-line front end (reference: eigentrust-cli/src/*)
- ``zk``       — constraint-system layer: circuits, gadgets, MockProver,
                 KZG/BN254 (reference: eigentrust-zk circuit side)
"""

__version__ = "0.1.0"
