"""Runtime platform selection helpers."""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS effective even when a sitecustomize has already
    pre-registered a different platform (this machine's TPU tunnel does:
    the env var alone is read too early to win). Call before first device
    use; safe no-op when the env var is unset or backends are already
    initialized."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError:
        pass
