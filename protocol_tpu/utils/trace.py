"""Structured tracing + typed metrics: the repo's instrumentation layer.

The reference has no observability beyond ad-hoc ``Instant`` timers
printed to the log (eigentrust/src/lib.rs:549-555, utils.rs:264-267,
dynamic_sets/native.rs:1121-1127) — SURVEY.md §5 marks real tracing as
net-new for this framework. This module provides:

- ``span(name, **fields)``: nested wall-clock spans (context manager)
  carrying ``span_id``/``parent_id`` and, when a trace context is
  active, the ``trace_id``(s) of the work items flowing through them;
- ``context(trace_id=...)`` / ``context(trace_ids=[...])``: thread-local
  trace-context propagation — a cheap id (attestation digest, job id,
  HTTP request id) stamped on every span/event emitted inside, so one
  work item's end-to-end path is joinable from the JSONL stream;
- **typed instruments** with Prometheus semantics, rendered by
  ``service/metrics.py`` with correct ``# TYPE`` metadata:
  ``counter(name)`` (monotonic, ``_total``), ``gauge(name)``, and
  ``histogram(name)`` (fixed log-spaced buckets, exact count/sum,
  ``_bucket``/``_sum``/``_count``), all label-aware (labels must be
  static strings in code — stable cardinality is the caller's contract);
- ``event(name, **fields)``: point events with arbitrary fields,
- legacy scalar samples via ``metric(name, value)`` (gauge view),
- a process-global ``Tracer`` with JSONL export and a summary table,
- ``device_trace(log_dir)``: optional passthrough to the JAX profiler
  (xprof) for device-side timelines — it emits start/stop events into
  the JSONL stream carrying the log dir and the active trace ids, so an
  xprof capture is joinable against the span stream offline;
- **sync-span mode** (``sync_spans()`` / ``PTPU_TRACE_SYNC=1``): device
  dispatch is asynchronous, so a span around a dispatch-only call
  attributes the compute cost to whichever later span happens to block.
  With sync mode on, ``device_sync(x)`` drains the device queue at span
  boundaries, making per-stage attribution accurate (at the cost of the
  production overlap — a profiling mode, not a serving default);
- **XLA compile tracking** (:class:`CompileTracker`): a
  ``jax.monitoring`` event listener recording every backend compile as
  ``ptpu_xla_compiles_total{site}`` + ``ptpu_xla_compile_seconds``,
  with ``compile_watch(site, signature)`` marking a code region — a
  compile inside a region whose signature was already compiled once is
  a *steady-state recompile* (a shape leak in a cache that should have
  hit), counted and latched as a warning the service surfaces on
  ``/status``.

Tracing is off unless enabled — ``enable()`` in code or the
``PROTOCOL_TPU_TRACE`` env var (set to a path to also stream JSONL
there; set to ``1`` for in-memory only). Overhead when disabled is one
attribute check per call site.

Thread-safety contract: recording, JSONL emission, and ``dump_jsonl``
are all safe against concurrent mutation — emits are serialized under a
dedicated lock (no interleaved lines), and dumps snapshot the buffers
under the collector lock before touching the file.
"""

from __future__ import annotations

import bisect
import contextlib
import itertools
import json
import math
import os
import threading
import time
from dataclasses import dataclass


# per-name metric history bound (samples kept for dump_jsonl); the
# latest value is never dropped — see Tracer.metric
METRIC_HISTORY_CAP = 4096

# per-span-name duration window for percentile estimates (p50/p95 on
# /stages and stage_summary): bounded per NAME so busy spans cannot
# evict quiet ones
DURATION_WINDOW_CAP = 512

# default histogram buckets: log-spaced (factor √10) from 100 µs to
# 100 s — WAL appends sit at the bottom, cold converges and proof jobs
# at the top (beyond lands in +Inf). Fixed in code so every scrape of a
# given series has identical bucket boundaries.
DEFAULT_BUCKETS = tuple(10.0 ** (e / 2.0) for e in range(-8, 5))

# ptpu_commit_batch_size counts COLUMNS per MSM batch, not seconds —
# integer buckets sized to the commit engine's grouping (K ≤ 16 per
# g1_msm_multi call). Every creation site must pass these (buckets are
# fixed at first registration): the commit engine and
# service/metrics.py declare_instruments.
COMMIT_BATCH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)

# ptpu_refresh_frontier_rows counts frontier/sample-set ROWS per
# sublinear refresh, not seconds — decade buckets spanning one dirty
# node to a 10M-peer graph. Every creation site must pass these
# (buckets are fixed at first registration): service/refresh.py
# _record_sublinear and service/metrics.py declare_instruments.
FRONTIER_ROWS_BUCKETS = (1.0, 10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7)


def _label_key(labels: dict) -> tuple:
    """Canonical (sorted, stringified) label identity for one series."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter (Prometheus ``counter``): only ever goes up.
    Survives :meth:`Tracer.reset` — a scraper must never see a counter
    move backwards short of a process restart."""

    kind = "counter"

    def __init__(self, name: str, tracer: "Tracer"):
        self.name = name
        self._tracer = tracer
        self._lock = threading.Lock()
        self._values: dict = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._tracer.enabled:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def set_total(self, value: float, **labels) -> None:
        """Adopt an externally-tracked running total (e.g. an existing
        ``self.retries`` attribute); clamped monotonic — the stored
        value never decreases."""
        if not self._tracer.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._values[key] = max(self._values.get(key, 0.0),
                                    float(value))

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> list:
        """[(label_items, value)] — a consistent copy for rendering."""
        with self._lock:
            return sorted(self._values.items())


class Gauge:
    """Last-write-wins scalar (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self, name: str, tracer: "Tracer"):
        self.name = name
        self._tracer = tracer
        self._lock = threading.Lock()
        self._values: dict = {}

    def set(self, value: float, **labels) -> None:
        if not self._tracer.enabled:
            return
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> list:
        with self._lock:
            return sorted(self._values.items())


class Histogram:
    """Fixed-bucket latency histogram with exact count/sum (Prometheus
    ``histogram``): per label set, one non-cumulative count per bucket
    plus an overflow (+Inf) slot — rendering cumulates. Buckets are
    fixed at first registration; later ``histogram(name)`` calls reuse
    them."""

    kind = "histogram"

    def __init__(self, name: str, tracer: "Tracer", buckets=None):
        self.name = name
        self._tracer = tracer
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._series: dict = {}

    def observe(self, value: float, **labels) -> None:
        if not self._tracer.enabled:
            return
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = {"counts": [0] * (len(self.buckets) + 1),
                     "sum": 0.0, "count": 0}
                self._series[key] = s
            s["counts"][idx] += 1
            s["sum"] += value
            s["count"] += 1

    def series(self) -> list:
        """[(label_items, {counts, sum, count})] — deep-copied so the
        renderer never races an observe."""
        with self._lock:
            return sorted(
                (key, {"counts": list(s["counts"]), "sum": s["sum"],
                       "count": s["count"]})
                for key, s in self._series.items())


class PendingTraces:
    """Trace ids handed from one pipeline stage to a later asynchronous
    one, keyed by a monotonically-increasing revision: the ingest sink
    ``add``s the ids it applied at graph revision R, and the refresher
    ``take``s everything at-or-below the revision it is about to
    publish — stamping the refresh span that first reflects those work
    items. Bounded (oldest dropped) so a stalled consumer is a gap in
    the trace stream, not a leak."""

    def __init__(self, cap: int = 4096):
        self._lock = threading.Lock()
        self._items: list = []  # [(revision, trace_id)]
        self._cap = cap

    def add(self, revision: int, trace_ids) -> None:
        with self._lock:
            self._items.extend((revision, t) for t in trace_ids)
            if len(self._items) > self._cap:
                del self._items[: len(self._items) - self._cap]

    def take(self, revision: int) -> list:
        """Drain every id recorded at-or-below ``revision``."""
        with self._lock:
            taken = [t for r, t in self._items if r <= revision]
            self._items = [(r, t) for r, t in self._items if r > revision]
        return taken


class CompileTracker:
    """XLA compile observability: one ``jax.monitoring`` listener for
    the process, feeding typed instruments and a steady-state recompile
    detector.

    Steady-state semantics: legitimate compiles happen whenever a new
    shape reaches a jitted entry point (a grown graph, a new circuit
    k). A compile for a (site, signature) pair that was ALREADY
    compiled once in this process means a cache that should have hit
    missed — a shape/weak-type leak in the refresh or prover cache —
    so it increments ``xla_steady_recompiles`` and latches
    :attr:`recompile_warning`. Callers pick the signature to mirror
    the jit cache key they expect to hit (shapes + static args).

    Thread model: ``jax.monitoring`` invokes listeners on the thread
    that runs the compile (the dispatching thread), so the per-thread
    compile count a :meth:`watch` reads cannot be inflated by a
    concurrent thread's compiles."""

    EVENT = "/jax/core/compile/backend_compile_duration"
    SEEN_CAP = 4096  # signature memory bound (long-lived daemons)

    def __init__(self, tracer: "Tracer"):
        self._tracer = tracer
        self._lock = threading.Lock()
        self._local = threading.local()
        self.installed = False
        self.compiles = 0
        self.compile_seconds = 0.0
        self.steady_recompiles = 0
        self.recompile_warning = False
        self.last_site: str | None = None
        self._seen: set = set()

    def install(self) -> bool:
        """Register the listener (idempotent); False when jax is
        unavailable — compile tracking degrades to a no-op, never an
        import error on jax-less hosts."""
        if self.installed:
            return True
        try:
            import jax.monitoring
        except Exception:  # pragma: no cover - jax-less host
            return False
        jax.monitoring.register_event_duration_secs_listener(
            self._on_event)
        self.installed = True
        return True

    def _on_event(self, event: str, duration: float, **kw) -> None:
        if event != self.EVENT or not self._tracer.enabled:
            return
        site = getattr(self._local, "site", None) or "other"
        with self._lock:
            self.compiles += 1
            self.compile_seconds += float(duration)
            self.last_site = site
        self._local.count = getattr(self._local, "count", 0) + 1
        self._local.seconds = (getattr(self._local, "seconds", 0.0)
                               + float(duration))
        self._tracer.counter("xla_compiles").inc(site=site)
        self._tracer.histogram("xla_compile_seconds").observe(
            float(duration), site=site)

    def thread_compiles(self) -> int:
        return getattr(self._local, "count", 0)

    def thread_compile_seconds(self) -> float:
        """Seconds THIS thread spent in backend compiles (the listener
        runs on the dispatching thread) — lets a timed region carve
        compile time out of its wall clock."""
        return getattr(self._local, "seconds", 0.0)

    @contextlib.contextmanager
    def watch(self, site: str, signature=None):
        """Attribute compiles inside the block to ``site``; with a
        ``signature``, latch the steady-state warning when this exact
        signature compiles a second time."""
        if not self._tracer.enabled:
            yield
            return
        self.install()
        prev = getattr(self._local, "site", None)
        self._local.site = site
        before = self.thread_compiles()
        try:
            yield
        finally:
            self._local.site = prev
            # > 0, not truthy: a concurrent reset() swaps the
            # thread-local store out from under an in-flight watch and
            # the delta goes negative — never latch or inc on that
            compiled = self.thread_compiles() - before
            if signature is not None and compiled > 0:
                key = (site, signature)
                with self._lock:
                    seen = key in self._seen
                    if not seen:
                        if len(self._seen) >= self.SEEN_CAP:
                            # bounded memory: dropping old signatures
                            # can only under-report, never false-latch
                            self._seen.pop()
                        self._seen.add(key)
                    else:
                        self.steady_recompiles += compiled
                        self.recompile_warning = True
                if seen:
                    self._tracer.counter("xla_steady_recompiles").inc(
                        compiled, site=site)
                    self._tracer.event("trace.steady_recompile",
                                       site=site, compiles=compiled)

    def reset(self) -> None:
        """Clear counters, the seen-signature set, and the warning
        latch (the listener stays installed). Test teardown seam —
        the latch is process-global, so a test that deliberately
        trips it must not leak the warning into later tests."""
        with self._lock:
            self.compiles = 0
            self.compile_seconds = 0.0
            self.steady_recompiles = 0
            self.recompile_warning = False
            self.last_site = None
            self._seen.clear()
        self._local = threading.local()

    def stats(self) -> dict:
        with self._lock:
            return {
                "installed": self.installed,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 6),
                "steady_recompiles": self.steady_recompiles,
                "recompile_warning": self.recompile_warning,
                "last_site": self.last_site,
            }


@dataclass
class SpanRecord:
    name: str
    start: float           # EPOCH seconds (time.time at span open) —
    duration: float        # alignable with event timestamps; duration
    depth: int             # is measured on the monotonic clock
    fields: dict
    span_id: str = ""
    parent_id: str | None = None
    trace_ids: tuple = ()


class Tracer:
    """Process-global collector. Thread-safe; spans nest per-thread."""

    def __init__(self):
        self.enabled = False
        # sync-span mode: device_sync() drains the device queue at span
        # boundaries for accurate stage attribution (PTPU_TRACE_SYNC's
        # first-class form; see module docstring)
        self.sync = False
        # fleet identity: once a process knows its place in the fleet
        # (leader daemon, follower replica, prove-worker) every emitted
        # record carries instance/role — the cross-process trace join
        # needs the attribution on the records themselves, because a
        # merged view has no other way to tell the streams apart
        self.instance: str | None = None
        self.role: str | None = None
        self.compile_tracker = CompileTracker(self)
        self._lock = threading.Lock()
        self._emit_lock = threading.Lock()
        self._local = threading.local()
        self._stream = None
        self._stream_path: str | None = None
        self._stream_bytes = 0
        # size cap on the JSONL stream (PTPU_TRACE_MAX_BYTES; 0 =
        # unbounded): past it the file rotates to <path>.1
        self._stream_max_bytes = 0
        self.spans: list = []
        self.events: list = []
        # per-name duration windows for percentile estimates: bounded
        # PER NAME (unlike the shared spans list) so a high-frequency
        # span (HTTP requests) cannot evict every sample of a rare but
        # important one (refresh, prover stages) out of /stages' p50/p95
        self._durations: dict = {}
        self.metrics: dict = {}
        self._instruments: dict = {}
        self._span_ids = itertools.count(1)
        # exact running aggregates per span name: summary() stays
        # correct even after the bounded spans list drops old records
        # (a daemon emits spans indefinitely)
        self._span_agg: dict = {}

    # --- lifecycle --------------------------------------------------------
    def enable(self, stream_path: str | None = None) -> None:
        self.enabled = True
        if stream_path:
            # re-enabling onto a new path must not leak the previous
            # stream's fd (e.g. PROTOCOL_TPU_TRACE env stream replaced
            # by a CLI --jsonl flag)
            old = self._stream
            self._stream = open(stream_path, "a", buffering=1)
            self._stream_path = stream_path
            try:
                self._stream_bytes = os.fstat(
                    self._stream.fileno()).st_size
            except OSError:
                self._stream_bytes = 0
            env = os.environ.get("PTPU_TRACE_MAX_BYTES")
            try:
                self._stream_max_bytes = int(env) if env else 0
            except ValueError:
                self._stream_max_bytes = 0
            if old is not None:
                with contextlib.suppress(OSError):
                    old.close()

    def disable(self) -> None:
        self.enabled = False
        if self._stream:
            self._stream.close()
            self._stream = None
            self._stream_path = None

    def _rotate_stream_locked(self) -> None:
        """Size-based rotation of the JSONL stream: the current file
        moves to ``<path>.1`` (one rotated sibling — ``obs --jsonl``
        reads it back) and a fresh file takes its place. Called under
        ``_emit_lock`` with the size cap already exceeded; any OS
        failure leaves the original stream in place (an unbounded
        trace beats a lost one)."""
        path = self._stream_path
        if not path:
            return
        old = self._stream
        try:
            os.replace(path, path + ".1")
            new = open(path, "a", buffering=1)
        except OSError:
            # replace failed: keep appending to the original; replace
            # succeeded but reopen failed: old fd still points at the
            # rotated inode, so no record is ever dropped either way
            return
        self._stream = new
        self._stream_bytes = 0
        if old is not None:
            with contextlib.suppress(OSError):
                old.close()

    def reset(self) -> None:
        """Clear spans/events/metric histories. Typed instruments are
        deliberately KEPT: counters are monotonic for the process
        lifetime (a /metrics scrape must never see one go backwards);
        use :meth:`reset_instruments` for a full teardown (tests)."""
        with self._lock:
            self.spans.clear()
            self.events.clear()
            self.metrics.clear()
            self._span_agg.clear()
            self._durations.clear()
        self.instance = None
        self.role = None

    def reset_instruments(self) -> None:
        with self._lock:
            self._instruments.clear()

    # --- typed instruments ------------------------------------------------
    def _instrument(self, name: str, cls, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, self, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"instrument {name!r} already registered as "
                    f"{inst.kind}, not {cls.kind}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._instrument(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._instrument(name, Gauge)

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._instrument(name, Histogram, buckets=buckets)

    def instruments(self) -> list:
        """Registered instruments, name-sorted (a consistent copy)."""
        with self._lock:
            return [inst for _, inst in sorted(self._instruments.items())]

    # --- trace context ----------------------------------------------------
    def new_id(self) -> str:
        """A process-unique short id (HTTP request ids, span ids)."""
        return f"{next(self._span_ids):08x}"

    def current_trace_ids(self) -> tuple:
        return getattr(self._local, "trace", ())

    @contextlib.contextmanager
    def context(self, trace_id: str | None = None, trace_ids=None):
        """Bind trace id(s) to this thread: every span/event emitted
        inside carries them (``trace_id`` when single, ``trace_ids``
        list otherwise). Nesting replaces, exit restores."""
        if not self.enabled:
            yield
            return
        ids = tuple(trace_ids) if trace_ids is not None else (
            (trace_id,) if trace_id else ())
        prev = getattr(self._local, "trace", ())
        self._local.trace = ids or prev
        try:
            yield
        finally:
            self._local.trace = prev

    def set_identity(self, instance: str, role: str) -> None:
        """Declare this process's fleet identity. Idempotent;
        subsequent spans/events carry ``instance``/``role``."""
        self.instance = str(instance)
        self.role = str(role)

    def _trace_fields(self) -> dict:
        ids = getattr(self._local, "trace", ())
        out: dict = {}
        if len(ids) == 1:
            out["trace_id"] = ids[0]
        elif ids:
            out["trace_ids"] = list(ids)
        worker = getattr(self._local, "worker", None)
        if worker is not None:
            out["worker"] = worker
        if self.instance is not None:
            out["instance"] = self.instance
            out["role"] = self.role
        return out

    # --- worker context ---------------------------------------------------
    def current_worker(self) -> str | None:
        return getattr(self._local, "worker", None)

    @contextlib.contextmanager
    def worker_context(self, name: str):
        """Bind a pool-worker identity to this thread: every span/event
        emitted inside carries ``worker: name`` (the ``obs --trace-id``
        view shows which worker executed a job's prover stages), and
        stage instruments that consult :func:`current_worker` label
        their series with it. Nesting replaces, exit restores — same
        discipline as :meth:`context`."""
        prev = getattr(self._local, "worker", None)
        self._local.worker = name
        try:
            yield
        finally:
            self._local.worker = prev

    # --- recording --------------------------------------------------------
    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        if not self.enabled:
            yield
            return
        depth = self._depth()
        stack = getattr(self._local, "stack", ())
        parent = stack[-1] if stack else None
        span_id = self.new_id()
        self._local.depth = depth + 1
        self._local.stack = stack + (span_id,)
        wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._local.depth = depth
            self._local.stack = stack
            trace_ids = getattr(self._local, "trace", ())
            worker = getattr(self._local, "worker", None)
            if worker is not None:
                # into the record's fields too, so dump_jsonl replays
                # carry the worker id exactly like the live stream
                fields.setdefault("worker", worker)
            rec = SpanRecord(name, wall, dt, depth, fields,
                             span_id=span_id, parent_id=parent,
                             trace_ids=trace_ids)
            with self._lock:
                self.spans.append(rec)
                if len(self.spans) > METRIC_HISTORY_CAP:
                    del self.spans[: len(self.spans) - METRIC_HISTORY_CAP]
                agg = self._span_agg.setdefault(
                    name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
                agg["count"] += 1
                agg["total_s"] += dt
                agg["max_s"] = max(agg["max_s"], dt)
                window = self._durations.setdefault(name, [])
                window.append(dt)
                if len(window) > DURATION_WINDOW_CAP:
                    del window[: len(window) - DURATION_WINDOW_CAP]
            obj = {"type": "span", "name": name, "ts": wall,
                   "duration_s": dt, "depth": depth, "span_id": span_id}
            if parent is not None:
                obj["parent_id"] = parent
            obj.update(self._trace_fields())
            obj.update(fields)
            self._emit(obj)

    def event(self, name: str, **fields) -> None:
        if not self.enabled:
            return
        ts = time.time()
        with self._lock:
            self.events.append((ts, name, fields))
            if len(self.events) > METRIC_HISTORY_CAP:
                del self.events[: len(self.events) - METRIC_HISTORY_CAP]
        obj = {"type": "event", "ts": ts, "name": name}
        obj.update(self._trace_fields())
        obj.update(fields)
        self._emit(obj)

    def metric(self, name: str, value) -> None:
        """Record a gauge/counter sample (last-write-wins + history).
        History is bounded per name: a long-running daemon samples
        counters continuously and an unbounded list is a slow leak —
        the latest value (what /metrics serves) is always kept."""
        if not self.enabled:
            return
        with self._lock:
            hist = self.metrics.setdefault(name, [])
            hist.append(float(value))
            if len(hist) > METRIC_HISTORY_CAP:
                del hist[: len(hist) - METRIC_HISTORY_CAP]
        self._emit({"type": "metric", "name": name, "value": float(value)})

    def metrics_latest(self) -> dict:
        """{name: most recent sample} — the gauge view Prometheus-style
        exporters (``service.metrics``) render."""
        with self._lock:
            return {k: v[-1] for k, v in self.metrics.items() if v}

    def _emit(self, obj: dict) -> None:
        stream = self._stream
        if stream is not None:
            line = json.dumps(obj) + "\n"
            # one lock, one write: concurrent emitters must never
            # interleave partial JSONL lines
            with self._emit_lock:
                try:
                    stream.write(line)
                except ValueError:  # stream closed under us (disable
                    return          # racing a daemon thread's emit)
                if self._stream_max_bytes > 0:
                    self._stream_bytes += len(line)
                    if self._stream_bytes > self._stream_max_bytes:
                        self._rotate_stream_locked()

    def emit_record(self, obj: dict) -> None:
        """Append one FOREIGN record (a span shipped from another fleet
        process via ``service/telemetry.py``) to this process's JSONL
        stream verbatim — the cross-process trace join lands remote
        spans next to local ones. No-op without an open stream."""
        self._emit(obj)

    def recent_spans(self, after_id: int = 0, limit: int = 256):
        """``(records, cursor)``: the newest ≤ ``limit`` retained spans
        whose numeric span id is > ``after_id``, serialized exactly like
        :meth:`dump_jsonl` and stamped with this process's
        instance/role. ``cursor`` is the highest id serialized (pass it
        back as ``after_id`` to ship each span at most once) — span ids
        are ``new_id()`` hex, monotonic for the process lifetime."""
        with self._lock:
            recs = [r for r in self.spans
                    if r.span_id and int(r.span_id, 16) > after_id]
        recs = recs[-int(limit):] if limit else []
        out = []
        cursor = after_id
        for rec in recs:
            obj = {"type": "span", "name": rec.name, "ts": rec.start,
                   "duration_s": rec.duration, "depth": rec.depth,
                   "span_id": rec.span_id}
            if rec.parent_id is not None:
                obj["parent_id"] = rec.parent_id
            if len(rec.trace_ids) == 1:
                obj["trace_id"] = rec.trace_ids[0]
            elif rec.trace_ids:
                obj["trace_ids"] = list(rec.trace_ids)
            obj.update(rec.fields)
            if self.instance is not None:
                obj.setdefault("instance", self.instance)
                obj.setdefault("role", self.role)
            out.append(obj)
            cursor = max(cursor, int(rec.span_id, 16))
        return out, cursor

    # --- reporting --------------------------------------------------------
    def summary(self) -> dict:
        """Aggregate span stats: {name: {count, total_s, max_s}} — from
        the exact running aggregates (immune to the bounded spans list
        trimming old records)."""
        with self._lock:
            return {name: dict(agg)
                    for name, agg in self._span_agg.items()}

    def span_durations(self) -> dict:
        """{name: [duration, ...]} from the PER-NAME bounded windows
        (newest ``DURATION_WINDOW_CAP`` per span name) — the percentile
        source for :func:`stage_summary`. Estimates over the retained
        window, unlike :meth:`summary` whose aggregates are exact."""
        with self._lock:
            return {name: list(window)
                    for name, window in self._durations.items()}

    def dump_jsonl(self, path: str) -> None:
        # snapshot under the lock FIRST: a daemon thread appending
        # mid-dump must not mutate the lists we iterate
        with self._lock:
            spans = list(self.spans)
            events = list(self.events)
            metrics = {k: list(v) for k, v in self.metrics.items()}
        with open(path, "w") as f:
            for rec in spans:
                obj = {"type": "span", "name": rec.name, "ts": rec.start,
                       "duration_s": rec.duration, "depth": rec.depth,
                       "span_id": rec.span_id}
                if rec.parent_id is not None:
                    obj["parent_id"] = rec.parent_id
                if len(rec.trace_ids) == 1:
                    obj["trace_id"] = rec.trace_ids[0]
                elif rec.trace_ids:
                    obj["trace_ids"] = list(rec.trace_ids)
                obj.update(rec.fields)
                f.write(json.dumps(obj) + "\n")
            for ts, name, fields in events:
                f.write(json.dumps(
                    {"type": "event", "ts": ts, "name": name, **fields})
                    + "\n")
            for name, values in metrics.items():
                f.write(json.dumps(
                    {"type": "metric", "name": name, "values": values})
                    + "\n")


def validate_record(obj) -> str | None:
    """Schema check for one JSONL trace record (the ``obs`` CLI verb's
    stream validator); returns an error string or None when valid."""
    if not isinstance(obj, dict):
        return "record is not a JSON object"
    kind = obj.get("type")
    if kind not in ("span", "event", "metric"):
        return f"unknown record type {kind!r}"
    name = obj.get("name")
    if not isinstance(name, str) or not name:
        return "missing/empty name"
    if kind == "span":
        if not isinstance(obj.get("duration_s"), (int, float)):
            return f"span {name!r} without numeric duration_s"
        if "span_id" in obj and not isinstance(obj["span_id"], str):
            return f"span {name!r} with non-string span_id"
    if kind == "metric":
        value = obj.get("value", obj.get("values"))
        if isinstance(value, list):
            if not all(isinstance(v, (int, float)) for v in value):
                return f"metric {name!r} with non-numeric values"
        elif not isinstance(value, (int, float)):
            return f"metric {name!r} without numeric value"
    return None


TRACER = Tracer()

if os.environ.get("PTPU_TRACE_SYNC") == "1":
    TRACER.sync = True

_env = os.environ.get("PROTOCOL_TPU_TRACE")
if _env:
    try:
        TRACER.enable(None if _env == "1" else _env)
    except OSError:  # unwritable stream path must not break imports
        TRACER.disable()
        TRACER.enabled = True  # keep in-memory tracing on


def enable(stream_path: str | None = None) -> None:
    TRACER.enable(stream_path)


def disable() -> None:
    TRACER.disable()


def span(name: str, **fields):
    return TRACER.span(name, **fields)


def event(name: str, **fields) -> None:
    TRACER.event(name, **fields)


def metric(name: str, value) -> None:
    TRACER.metric(name, value)


def counter(name: str) -> Counter:
    return TRACER.counter(name)


def counter_total(name: str, **labels) -> float:
    """Sum of a named counter's samples, optionally restricted to the
    label values given (compared stringified, the stored form) — the
    one instrument-scan idiom bench, the smoke and the tests kept
    re-implementing, each slightly differently."""
    want = {str(k): str(v) for k, v in labels.items()}
    for inst in TRACER.instruments():
        if inst.name == name and inst.kind == "counter":
            return sum(v for items, v in inst.samples()
                       if all(dict(items).get(k) == w
                              for k, w in want.items()))
    return 0.0


def gauge(name: str) -> Gauge:
    return TRACER.gauge(name)


def histogram(name: str, buckets=None) -> Histogram:
    return TRACER.histogram(name, buckets=buckets)


def context(trace_id: str | None = None, trace_ids=None):
    return TRACER.context(trace_id=trace_id, trace_ids=trace_ids)


def worker_context(name: str):
    return TRACER.worker_context(name)


def current_worker() -> str | None:
    return TRACER.current_worker()


def current_trace_ids() -> tuple:
    return TRACER.current_trace_ids()


def set_identity(instance: str, role: str) -> None:
    TRACER.set_identity(instance, role)


def emit_record(obj: dict) -> None:
    TRACER.emit_record(obj)


def recent_spans(after_id: int = 0, limit: int = 256):
    return TRACER.recent_spans(after_id=after_id, limit=limit)


def new_id() -> str:
    return TRACER.new_id()


def summary() -> dict:
    return TRACER.summary()


@contextlib.contextmanager
def timed(histogram_name: str, span_name: str, labels: dict | None = None,
          **fields):
    """A span that also feeds a latency histogram: the one timing idiom
    behind every stage instrument (prover stages, prove totals, the
    routed plan build), so the span/observe pairing cannot drift per
    site. ``labels`` go to the histogram series; ``fields`` to the
    span. The observation lands even when the body raises — a failed
    stage must stay visible to the histograms (and their count must
    keep matching the span count)."""
    t0 = time.perf_counter()
    try:
        with TRACER.span(span_name, **fields):
            yield
    finally:
        TRACER.histogram(histogram_name).observe(
            time.perf_counter() - t0, **(labels or {}))


# --- sync-span mode ---------------------------------------------------------

def sync_spans(enable: bool = True) -> None:
    """Turn sync-span mode on/off: :func:`device_sync` then drains the
    device queue at span boundaries, so per-stage spans attribute the
    device compute they dispatched instead of skewing it onto whichever
    later span happens to block. Profiling mode — it serializes stages,
    so totals read slightly worse than the production overlap.
    ``PTPU_TRACE_SYNC=1`` in the environment enables it at import."""
    TRACER.sync = bool(enable)


def sync_enabled() -> bool:
    return TRACER.sync


def device_sync(x):
    """Block until ``x`` (a device array / pytree) is ready when
    sync-span mode is active; returns ``x`` either way. Safe on
    jax-less hosts and on host-side values."""
    if TRACER.sync and TRACER.enabled and x is not None:
        try:
            import jax

            jax.block_until_ready(x)
        except Exception:  # noqa: BLE001 - host value / jax-less box
            pass
    return x


# --- XLA compile tracking ---------------------------------------------------

def install_compile_tracking() -> bool:
    """Register the process-wide compile listener (idempotent); returns
    False on a jax-less host."""
    return TRACER.compile_tracker.install()


def compile_watch(site: str, signature=None):
    """Context manager: attribute XLA compiles inside to ``site``
    (labels ``ptpu_xla_compiles_total``); with ``signature``, a compile
    for an already-seen signature counts as a steady-state recompile
    and latches the warning (see :class:`CompileTracker`)."""
    return TRACER.compile_tracker.watch(site, signature)


def thread_compile_seconds() -> float:
    """Seconds this thread spent in XLA backend compiles; diff across a
    timed region to separate compile from execute wall time."""
    return TRACER.compile_tracker.thread_compile_seconds()


def compile_stats() -> dict:
    return TRACER.compile_tracker.stats()


# --- percentile stage summaries ---------------------------------------------

def percentile(values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of a non-empty list:
    the smallest value with at least ``q`` of the mass at or below it."""
    ordered = sorted(values)
    if not ordered:
        raise ValueError("percentile of empty list")
    rank = math.ceil(q * len(ordered))
    return ordered[max(rank, 1) - 1]


def stage_summary() -> dict:
    """Per-span-name durations with percentiles:
    ``{name: {count, total_s, max_s, p50_s, p95_s}}``. Counts/totals
    are the exact running aggregates; p50/p95 come from the bounded
    span window (daemon-safe estimates)."""
    exact = TRACER.summary()
    windows = TRACER.span_durations()
    out = {}
    for name, agg in exact.items():
        durations = windows.get(name) or []
        out[name] = {
            "count": agg["count"],
            "total_s": agg["total_s"],
            "max_s": agg["max_s"],
            "p50_s": percentile(durations, 0.50) if durations else 0.0,
            "p95_s": percentile(durations, 0.95) if durations else 0.0,
        }
    return out


@contextlib.contextmanager
def device_trace(log_dir: str):
    """JAX profiler (xprof) passthrough for device-side timelines; pair
    with ``tensorboard --logdir`` offline. No-op context on failure so
    production paths never die on profiler availability.

    Start/stop events land in the JSONL stream carrying ``log_dir`` and
    the active trace context, so an offline xprof timeline is joinable
    against the span stream by trace id + wall-clock window."""
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - jax-less host / profiler
        started = False  # unavailable: no-op context, never an error
    event("trace.device_trace_start", log_dir=str(log_dir),
          started=started)
    try:
        yield
    finally:
        if started:
            with contextlib.suppress(Exception):
                jax.profiler.stop_trace()
        event("trace.device_trace_stop", log_dir=str(log_dir),
              started=started)
