"""Structured tracing + metrics.

The reference has no observability beyond ad-hoc ``Instant`` timers
printed to the log (eigentrust/src/lib.rs:549-555, utils.rs:264-267,
dynamic_sets/native.rs:1121-1127) — SURVEY.md §5 marks real tracing as
net-new for this framework. This module provides:

- ``span(name, **fields)``: nested wall-clock spans (context manager),
- ``event(name, **fields)``: point events with arbitrary fields,
- counters/gauges via ``metric(name, value)``,
- a process-global ``Tracer`` with JSONL export and a summary table,
- ``device_trace(log_dir)``: optional passthrough to the JAX profiler
  (xprof) for device-side timelines.

Tracing is off unless enabled — ``enable()`` in code or the
``PROTOCOL_TPU_TRACE`` env var (set to a path to also stream JSONL
there; set to ``1`` for in-memory only). Overhead when disabled is one
attribute check per call site.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field


# per-name metric history bound (samples kept for dump_jsonl); the
# latest value is never dropped — see Tracer.metric
METRIC_HISTORY_CAP = 4096


@dataclass
class SpanRecord:
    name: str
    start: float
    duration: float
    depth: int
    fields: dict


class Tracer:
    """Process-global collector. Thread-safe; spans nest per-thread."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._local = threading.local()
        self._stream = None
        self.spans: list = []
        self.events: list = []
        self.metrics: dict = {}
        # exact running aggregates per span name: summary() stays
        # correct even after the bounded spans list drops old records
        # (a daemon emits spans indefinitely)
        self._span_agg: dict = {}

    # --- lifecycle --------------------------------------------------------
    def enable(self, stream_path: str | None = None) -> None:
        self.enabled = True
        if stream_path:
            self._stream = open(stream_path, "a", buffering=1)

    def disable(self) -> None:
        self.enabled = False
        if self._stream:
            self._stream.close()
            self._stream = None

    def reset(self) -> None:
        with self._lock:
            self.spans.clear()
            self.events.clear()
            self.metrics.clear()
            self._span_agg.clear()

    # --- recording --------------------------------------------------------
    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        if not self.enabled:
            yield
            return
        depth = self._depth()
        self._local.depth = depth + 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._local.depth = depth
            rec = SpanRecord(name, t0, dt, depth, fields)
            with self._lock:
                self.spans.append(rec)
                if len(self.spans) > METRIC_HISTORY_CAP:
                    del self.spans[: len(self.spans) - METRIC_HISTORY_CAP]
                agg = self._span_agg.setdefault(
                    name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
                agg["count"] += 1
                agg["total_s"] += dt
                agg["max_s"] = max(agg["max_s"], dt)
            self._emit({"type": "span", "name": name, "duration_s": dt,
                        "depth": depth, **fields})

    def event(self, name: str, **fields) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.events.append((time.time(), name, fields))
            if len(self.events) > METRIC_HISTORY_CAP:
                del self.events[: len(self.events) - METRIC_HISTORY_CAP]
        self._emit({"type": "event", "name": name, **fields})

    def metric(self, name: str, value) -> None:
        """Record a gauge/counter sample (last-write-wins + history).
        History is bounded per name: a long-running daemon samples
        counters continuously and an unbounded list is a slow leak —
        the latest value (what /metrics serves) is always kept."""
        if not self.enabled:
            return
        with self._lock:
            hist = self.metrics.setdefault(name, [])
            hist.append(float(value))
            if len(hist) > METRIC_HISTORY_CAP:
                del hist[: len(hist) - METRIC_HISTORY_CAP]
        self._emit({"type": "metric", "name": name, "value": float(value)})

    def metrics_latest(self) -> dict:
        """{name: most recent sample} — the gauge view Prometheus-style
        exporters (``service.metrics``) render."""
        with self._lock:
            return {k: v[-1] for k, v in self.metrics.items() if v}

    def _emit(self, obj: dict) -> None:
        if self._stream is not None:
            self._stream.write(json.dumps(obj) + "\n")

    # --- reporting --------------------------------------------------------
    def summary(self) -> dict:
        """Aggregate span stats: {name: {count, total_s, max_s}} — from
        the exact running aggregates (immune to the bounded spans list
        trimming old records)."""
        with self._lock:
            return {name: dict(agg)
                    for name, agg in self._span_agg.items()}

    def dump_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for rec in self.spans:
                f.write(json.dumps({
                    "type": "span", "name": rec.name, "start": rec.start,
                    "duration_s": rec.duration, "depth": rec.depth,
                    **rec.fields}) + "\n")
            for ts, name, fields in self.events:
                f.write(json.dumps(
                    {"type": "event", "ts": ts, "name": name, **fields}) + "\n")
            for name, values in self.metrics.items():
                f.write(json.dumps(
                    {"type": "metric", "name": name, "values": values}) + "\n")


TRACER = Tracer()

_env = os.environ.get("PROTOCOL_TPU_TRACE")
if _env:
    try:
        TRACER.enable(None if _env == "1" else _env)
    except OSError:  # unwritable stream path must not break imports
        TRACER.disable()
        TRACER.enabled = True  # keep in-memory tracing on


def enable(stream_path: str | None = None) -> None:
    TRACER.enable(stream_path)


def disable() -> None:
    TRACER.disable()


def span(name: str, **fields):
    return TRACER.span(name, **fields)


def event(name: str, **fields) -> None:
    TRACER.event(name, **fields)


def metric(name: str, value) -> None:
    TRACER.metric(name, value)


def summary() -> dict:
    return TRACER.summary()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """JAX profiler (xprof) passthrough for device-side timelines; pair
    with ``tensorboard --logdir`` offline. No-op context on failure so
    production paths never die on profiler availability."""
    import jax

    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - profiler unavailable
        started = False
    try:
        yield
    finally:
        if started:
            with contextlib.suppress(Exception):
                jax.profiler.stop_trace()
