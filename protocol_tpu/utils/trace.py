"""Structured tracing + typed metrics: the repo's instrumentation layer.

The reference has no observability beyond ad-hoc ``Instant`` timers
printed to the log (eigentrust/src/lib.rs:549-555, utils.rs:264-267,
dynamic_sets/native.rs:1121-1127) — SURVEY.md §5 marks real tracing as
net-new for this framework. This module provides:

- ``span(name, **fields)``: nested wall-clock spans (context manager)
  carrying ``span_id``/``parent_id`` and, when a trace context is
  active, the ``trace_id``(s) of the work items flowing through them;
- ``context(trace_id=...)`` / ``context(trace_ids=[...])``: thread-local
  trace-context propagation — a cheap id (attestation digest, job id,
  HTTP request id) stamped on every span/event emitted inside, so one
  work item's end-to-end path is joinable from the JSONL stream;
- **typed instruments** with Prometheus semantics, rendered by
  ``service/metrics.py`` with correct ``# TYPE`` metadata:
  ``counter(name)`` (monotonic, ``_total``), ``gauge(name)``, and
  ``histogram(name)`` (fixed log-spaced buckets, exact count/sum,
  ``_bucket``/``_sum``/``_count``), all label-aware (labels must be
  static strings in code — stable cardinality is the caller's contract);
- ``event(name, **fields)``: point events with arbitrary fields,
- legacy scalar samples via ``metric(name, value)`` (gauge view),
- a process-global ``Tracer`` with JSONL export and a summary table,
- ``device_trace(log_dir)``: optional passthrough to the JAX profiler
  (xprof) for device-side timelines.

Tracing is off unless enabled — ``enable()`` in code or the
``PROTOCOL_TPU_TRACE`` env var (set to a path to also stream JSONL
there; set to ``1`` for in-memory only). Overhead when disabled is one
attribute check per call site.

Thread-safety contract: recording, JSONL emission, and ``dump_jsonl``
are all safe against concurrent mutation — emits are serialized under a
dedicated lock (no interleaved lines), and dumps snapshot the buffers
under the collector lock before touching the file.
"""

from __future__ import annotations

import bisect
import contextlib
import itertools
import json
import os
import threading
import time
from dataclasses import dataclass


# per-name metric history bound (samples kept for dump_jsonl); the
# latest value is never dropped — see Tracer.metric
METRIC_HISTORY_CAP = 4096

# default histogram buckets: log-spaced (factor √10) from 100 µs to
# 100 s — WAL appends sit at the bottom, cold converges and proof jobs
# at the top (beyond lands in +Inf). Fixed in code so every scrape of a
# given series has identical bucket boundaries.
DEFAULT_BUCKETS = tuple(10.0 ** (e / 2.0) for e in range(-8, 5))


def _label_key(labels: dict) -> tuple:
    """Canonical (sorted, stringified) label identity for one series."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter (Prometheus ``counter``): only ever goes up.
    Survives :meth:`Tracer.reset` — a scraper must never see a counter
    move backwards short of a process restart."""

    kind = "counter"

    def __init__(self, name: str, tracer: "Tracer"):
        self.name = name
        self._tracer = tracer
        self._lock = threading.Lock()
        self._values: dict = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._tracer.enabled:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def set_total(self, value: float, **labels) -> None:
        """Adopt an externally-tracked running total (e.g. an existing
        ``self.retries`` attribute); clamped monotonic — the stored
        value never decreases."""
        if not self._tracer.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._values[key] = max(self._values.get(key, 0.0),
                                    float(value))

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> list:
        """[(label_items, value)] — a consistent copy for rendering."""
        with self._lock:
            return sorted(self._values.items())


class Gauge:
    """Last-write-wins scalar (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self, name: str, tracer: "Tracer"):
        self.name = name
        self._tracer = tracer
        self._lock = threading.Lock()
        self._values: dict = {}

    def set(self, value: float, **labels) -> None:
        if not self._tracer.enabled:
            return
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> list:
        with self._lock:
            return sorted(self._values.items())


class Histogram:
    """Fixed-bucket latency histogram with exact count/sum (Prometheus
    ``histogram``): per label set, one non-cumulative count per bucket
    plus an overflow (+Inf) slot — rendering cumulates. Buckets are
    fixed at first registration; later ``histogram(name)`` calls reuse
    them."""

    kind = "histogram"

    def __init__(self, name: str, tracer: "Tracer", buckets=None):
        self.name = name
        self._tracer = tracer
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._series: dict = {}

    def observe(self, value: float, **labels) -> None:
        if not self._tracer.enabled:
            return
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = {"counts": [0] * (len(self.buckets) + 1),
                     "sum": 0.0, "count": 0}
                self._series[key] = s
            s["counts"][idx] += 1
            s["sum"] += value
            s["count"] += 1

    def series(self) -> list:
        """[(label_items, {counts, sum, count})] — deep-copied so the
        renderer never races an observe."""
        with self._lock:
            return sorted(
                (key, {"counts": list(s["counts"]), "sum": s["sum"],
                       "count": s["count"]})
                for key, s in self._series.items())


class PendingTraces:
    """Trace ids handed from one pipeline stage to a later asynchronous
    one, keyed by a monotonically-increasing revision: the ingest sink
    ``add``s the ids it applied at graph revision R, and the refresher
    ``take``s everything at-or-below the revision it is about to
    publish — stamping the refresh span that first reflects those work
    items. Bounded (oldest dropped) so a stalled consumer is a gap in
    the trace stream, not a leak."""

    def __init__(self, cap: int = 4096):
        self._lock = threading.Lock()
        self._items: list = []  # [(revision, trace_id)]
        self._cap = cap

    def add(self, revision: int, trace_ids) -> None:
        with self._lock:
            self._items.extend((revision, t) for t in trace_ids)
            if len(self._items) > self._cap:
                del self._items[: len(self._items) - self._cap]

    def take(self, revision: int) -> list:
        """Drain every id recorded at-or-below ``revision``."""
        with self._lock:
            taken = [t for r, t in self._items if r <= revision]
            self._items = [(r, t) for r, t in self._items if r > revision]
        return taken


@dataclass
class SpanRecord:
    name: str
    start: float           # EPOCH seconds (time.time at span open) —
    duration: float        # alignable with event timestamps; duration
    depth: int             # is measured on the monotonic clock
    fields: dict
    span_id: str = ""
    parent_id: str | None = None
    trace_ids: tuple = ()


class Tracer:
    """Process-global collector. Thread-safe; spans nest per-thread."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._emit_lock = threading.Lock()
        self._local = threading.local()
        self._stream = None
        self.spans: list = []
        self.events: list = []
        self.metrics: dict = {}
        self._instruments: dict = {}
        self._span_ids = itertools.count(1)
        # exact running aggregates per span name: summary() stays
        # correct even after the bounded spans list drops old records
        # (a daemon emits spans indefinitely)
        self._span_agg: dict = {}

    # --- lifecycle --------------------------------------------------------
    def enable(self, stream_path: str | None = None) -> None:
        self.enabled = True
        if stream_path:
            self._stream = open(stream_path, "a", buffering=1)

    def disable(self) -> None:
        self.enabled = False
        if self._stream:
            self._stream.close()
            self._stream = None

    def reset(self) -> None:
        """Clear spans/events/metric histories. Typed instruments are
        deliberately KEPT: counters are monotonic for the process
        lifetime (a /metrics scrape must never see one go backwards);
        use :meth:`reset_instruments` for a full teardown (tests)."""
        with self._lock:
            self.spans.clear()
            self.events.clear()
            self.metrics.clear()
            self._span_agg.clear()

    def reset_instruments(self) -> None:
        with self._lock:
            self._instruments.clear()

    # --- typed instruments ------------------------------------------------
    def _instrument(self, name: str, cls, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, self, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"instrument {name!r} already registered as "
                    f"{inst.kind}, not {cls.kind}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._instrument(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._instrument(name, Gauge)

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._instrument(name, Histogram, buckets=buckets)

    def instruments(self) -> list:
        """Registered instruments, name-sorted (a consistent copy)."""
        with self._lock:
            return [inst for _, inst in sorted(self._instruments.items())]

    # --- trace context ----------------------------------------------------
    def new_id(self) -> str:
        """A process-unique short id (HTTP request ids, span ids)."""
        return f"{next(self._span_ids):08x}"

    def current_trace_ids(self) -> tuple:
        return getattr(self._local, "trace", ())

    @contextlib.contextmanager
    def context(self, trace_id: str | None = None, trace_ids=None):
        """Bind trace id(s) to this thread: every span/event emitted
        inside carries them (``trace_id`` when single, ``trace_ids``
        list otherwise). Nesting replaces, exit restores."""
        if not self.enabled:
            yield
            return
        ids = tuple(trace_ids) if trace_ids is not None else (
            (trace_id,) if trace_id else ())
        prev = getattr(self._local, "trace", ())
        self._local.trace = ids or prev
        try:
            yield
        finally:
            self._local.trace = prev

    def _trace_fields(self) -> dict:
        ids = getattr(self._local, "trace", ())
        if not ids:
            return {}
        if len(ids) == 1:
            return {"trace_id": ids[0]}
        return {"trace_ids": list(ids)}

    # --- recording --------------------------------------------------------
    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        if not self.enabled:
            yield
            return
        depth = self._depth()
        stack = getattr(self._local, "stack", ())
        parent = stack[-1] if stack else None
        span_id = self.new_id()
        self._local.depth = depth + 1
        self._local.stack = stack + (span_id,)
        wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._local.depth = depth
            self._local.stack = stack
            trace_ids = getattr(self._local, "trace", ())
            rec = SpanRecord(name, wall, dt, depth, fields,
                             span_id=span_id, parent_id=parent,
                             trace_ids=trace_ids)
            with self._lock:
                self.spans.append(rec)
                if len(self.spans) > METRIC_HISTORY_CAP:
                    del self.spans[: len(self.spans) - METRIC_HISTORY_CAP]
                agg = self._span_agg.setdefault(
                    name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
                agg["count"] += 1
                agg["total_s"] += dt
                agg["max_s"] = max(agg["max_s"], dt)
            obj = {"type": "span", "name": name, "ts": wall,
                   "duration_s": dt, "depth": depth, "span_id": span_id}
            if parent is not None:
                obj["parent_id"] = parent
            obj.update(self._trace_fields())
            obj.update(fields)
            self._emit(obj)

    def event(self, name: str, **fields) -> None:
        if not self.enabled:
            return
        ts = time.time()
        with self._lock:
            self.events.append((ts, name, fields))
            if len(self.events) > METRIC_HISTORY_CAP:
                del self.events[: len(self.events) - METRIC_HISTORY_CAP]
        obj = {"type": "event", "ts": ts, "name": name}
        obj.update(self._trace_fields())
        obj.update(fields)
        self._emit(obj)

    def metric(self, name: str, value) -> None:
        """Record a gauge/counter sample (last-write-wins + history).
        History is bounded per name: a long-running daemon samples
        counters continuously and an unbounded list is a slow leak —
        the latest value (what /metrics serves) is always kept."""
        if not self.enabled:
            return
        with self._lock:
            hist = self.metrics.setdefault(name, [])
            hist.append(float(value))
            if len(hist) > METRIC_HISTORY_CAP:
                del hist[: len(hist) - METRIC_HISTORY_CAP]
        self._emit({"type": "metric", "name": name, "value": float(value)})

    def metrics_latest(self) -> dict:
        """{name: most recent sample} — the gauge view Prometheus-style
        exporters (``service.metrics``) render."""
        with self._lock:
            return {k: v[-1] for k, v in self.metrics.items() if v}

    def _emit(self, obj: dict) -> None:
        stream = self._stream
        if stream is not None:
            line = json.dumps(obj) + "\n"
            # one lock, one write: concurrent emitters must never
            # interleave partial JSONL lines
            with self._emit_lock:
                try:
                    stream.write(line)
                except ValueError:  # stream closed under us (disable
                    pass            # racing a daemon thread's emit)

    # --- reporting --------------------------------------------------------
    def summary(self) -> dict:
        """Aggregate span stats: {name: {count, total_s, max_s}} — from
        the exact running aggregates (immune to the bounded spans list
        trimming old records)."""
        with self._lock:
            return {name: dict(agg)
                    for name, agg in self._span_agg.items()}

    def dump_jsonl(self, path: str) -> None:
        # snapshot under the lock FIRST: a daemon thread appending
        # mid-dump must not mutate the lists we iterate
        with self._lock:
            spans = list(self.spans)
            events = list(self.events)
            metrics = {k: list(v) for k, v in self.metrics.items()}
        with open(path, "w") as f:
            for rec in spans:
                obj = {"type": "span", "name": rec.name, "ts": rec.start,
                       "duration_s": rec.duration, "depth": rec.depth,
                       "span_id": rec.span_id}
                if rec.parent_id is not None:
                    obj["parent_id"] = rec.parent_id
                if len(rec.trace_ids) == 1:
                    obj["trace_id"] = rec.trace_ids[0]
                elif rec.trace_ids:
                    obj["trace_ids"] = list(rec.trace_ids)
                obj.update(rec.fields)
                f.write(json.dumps(obj) + "\n")
            for ts, name, fields in events:
                f.write(json.dumps(
                    {"type": "event", "ts": ts, "name": name, **fields})
                    + "\n")
            for name, values in metrics.items():
                f.write(json.dumps(
                    {"type": "metric", "name": name, "values": values})
                    + "\n")


def validate_record(obj) -> str | None:
    """Schema check for one JSONL trace record (the ``obs`` CLI verb's
    stream validator); returns an error string or None when valid."""
    if not isinstance(obj, dict):
        return "record is not a JSON object"
    kind = obj.get("type")
    if kind not in ("span", "event", "metric"):
        return f"unknown record type {kind!r}"
    name = obj.get("name")
    if not isinstance(name, str) or not name:
        return "missing/empty name"
    if kind == "span":
        if not isinstance(obj.get("duration_s"), (int, float)):
            return f"span {name!r} without numeric duration_s"
        if "span_id" in obj and not isinstance(obj["span_id"], str):
            return f"span {name!r} with non-string span_id"
    if kind == "metric":
        value = obj.get("value", obj.get("values"))
        if isinstance(value, list):
            if not all(isinstance(v, (int, float)) for v in value):
                return f"metric {name!r} with non-numeric values"
        elif not isinstance(value, (int, float)):
            return f"metric {name!r} without numeric value"
    return None


TRACER = Tracer()

_env = os.environ.get("PROTOCOL_TPU_TRACE")
if _env:
    try:
        TRACER.enable(None if _env == "1" else _env)
    except OSError:  # unwritable stream path must not break imports
        TRACER.disable()
        TRACER.enabled = True  # keep in-memory tracing on


def enable(stream_path: str | None = None) -> None:
    TRACER.enable(stream_path)


def disable() -> None:
    TRACER.disable()


def span(name: str, **fields):
    return TRACER.span(name, **fields)


def event(name: str, **fields) -> None:
    TRACER.event(name, **fields)


def metric(name: str, value) -> None:
    TRACER.metric(name, value)


def counter(name: str) -> Counter:
    return TRACER.counter(name)


def gauge(name: str) -> Gauge:
    return TRACER.gauge(name)


def histogram(name: str, buckets=None) -> Histogram:
    return TRACER.histogram(name, buckets=buckets)


def context(trace_id: str | None = None, trace_ids=None):
    return TRACER.context(trace_id=trace_id, trace_ids=trace_ids)


def current_trace_ids() -> tuple:
    return TRACER.current_trace_ids()


def new_id() -> str:
    return TRACER.new_id()


def summary() -> dict:
    return TRACER.summary()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """JAX profiler (xprof) passthrough for device-side timelines; pair
    with ``tensorboard --logdir`` offline. No-op context on failure so
    production paths never die on profiler availability."""
    import jax

    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - profiler unavailable
        started = False
    try:
        yield
    finally:
        if started:
            with contextlib.suppress(Exception):
                jax.profiler.stop_trace()
