"""Framework error taxonomy.

Mirrors the reference's 19-variant ``EigenError`` enum
(``eigentrust/src/error.rs``) as a single exception class with a ``kind``
discriminator, which is the Pythonic shape for the same information.
"""

from __future__ import annotations


class EigenError(Exception):
    """Error with a machine-readable ``kind`` matching the reference enum."""

    KINDS = frozenset(
        {
            "connection_error",
            "conversion_error",
            "parsing_error",
            "file_io_error",
            "attestation_error",
            "keys_error",
            "proving_error",
            "verification_error",
            "network_error",
            "contract_error",
            "config_error",
            "request_error",
            "resource_error",
            "transaction_error",
            "unknown_error",
            "validation_error",
            "read_write_error",
            "recovery_error",
            "backend_error",
            # framework-specific: circuit construction/satisfiability
            # (the reference surfaces these as halo2 VerifyFailure values)
            "circuit_error",
            # service layer (protocol_tpu.service): queue backpressure /
            # drain rejection, and the chaos seam's synthetic failures
            "service_busy",
            # the proof pool's hard byte-budget ceiling (HTTP 503, vs
            # the tiered 429 service_busy sheds)
            "over_capacity",
            "injected_fault",
        }
    )

    def __init__(self, kind: str, message: str = ""):
        if kind not in self.KINDS:
            raise ValueError(f"unknown error kind: {kind}")
        self.kind = kind
        super().__init__(f"{kind}: {message}" if message else kind)
