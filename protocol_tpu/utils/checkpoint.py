"""Iteration checkpoint / resume for long convergence runs.

The reference persists only final artifacts (params/keys/proofs via the
``Storage`` trait + EigenFile layout, eigentrust/src/storage.rs:25-33,
eigentrust-cli/src/fs.rs:50-84) — runs are seconds-long at N=4 so
mid-computation checkpointing doesn't exist. At 10M peers SURVEY.md §5
requires real iteration checkpointing: a crashed or preempted shard run
must resume from the last completed chunk, not from iteration 0.

Design: numpy ``.npz`` payload + JSON sidecar metadata, written
atomically (tmp + rename) so a partially-written checkpoint is never
observed; ``keep`` bounds disk usage; ``latest()``/``restore()`` drive
resume. Device arrays are fetched to host once per checkpoint interval —
the interval amortizes the transfer, and the payload is just the score
vector (O(n) floats), not the operator.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import time

import numpy as np

from .errors import EigenError


class CheckpointManager:
    """Step-indexed checkpoints: ``step-{i}.npz`` + ``step-{i}.json``."""

    def __init__(self, directory: str, keep: int = 2):
        if keep < 1:
            raise EigenError("config_error", "keep must be >= 1")
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    # --- write ------------------------------------------------------------
    def save(self, step: int, arrays: dict, meta: dict | None = None) -> str:
        """Atomically persist ``arrays`` (name → ndarray) at ``step``."""
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        base = os.path.join(self.directory, f"step-{step:012d}")
        tmp = base + ".tmp.npz"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, base + ".npz")

        sidecar = {
            "step": step,
            "written_at": time.time(),
            "arrays": {k: list(v.shape) for k, v in arrays.items()},
            **(meta or {}),
        }
        tmp_meta = base + ".tmp.json"
        with open(tmp_meta, "w") as f:
            json.dump(sidecar, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_meta, base + ".json")

        self._gc()
        return base + ".npz"

    def _gc(self) -> None:
        steps = self.steps()
        for step in steps[: -self.keep]:
            base = os.path.join(self.directory, f"step-{step:012d}")
            for suffix in (".npz", ".json"):
                try:
                    os.remove(base + suffix)
                except FileNotFoundError:
                    pass
        # orphan payloads (crash between the payload and sidecar renames)
        # never appear in steps() and would otherwise accumulate forever.
        # Safe here because _gc runs in the writer process after its own
        # sidecar rename completed (single-writer assumption).
        live = set(steps)
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"step-(\d{12})\.npz", name)
            if m and int(m.group(1)) not in live:
                with contextlib.suppress(OSError):
                    os.remove(os.path.join(self.directory, name))

    # --- read -------------------------------------------------------------
    def steps(self) -> list:
        """Completed checkpoint steps, ascending. A checkpoint counts
        only when both payload and sidecar exist (atomic-rename order
        guarantees payload-before-sidecar). Leftover ``*.tmp.*`` files
        from a crash mid-save are ignored (and swept) rather than
        breaking resume."""
        out = []
        for name in os.listdir(self.directory):
            if ".tmp." in name:
                with contextlib.suppress(OSError):
                    os.remove(os.path.join(self.directory, name))
                continue
            m = re.fullmatch(r"step-(\d{12})\.json", name)
            if m:
                step = int(m.group(1))
                if os.path.exists(
                    os.path.join(self.directory, f"step-{step:012d}.npz")
                ):
                    out.append(step)
        return sorted(out)

    def latest(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step: int | None = None) -> tuple:
        """Returns (step, arrays, meta); ``step=None`` → latest."""
        if step is None:
            step = self.latest()
            if step is None:
                raise EigenError("file_io_error", "no checkpoint to restore")
        base = os.path.join(self.directory, f"step-{step:012d}")
        try:
            with np.load(base + ".npz") as z:
                arrays = {k: z[k] for k in z.files}
            with open(base + ".json") as f:
                meta = json.load(f)
        except FileNotFoundError as e:
            raise EigenError("file_io_error",
                             f"checkpoint step {step} missing") from e
        return step, arrays, meta
