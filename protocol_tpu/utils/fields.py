"""Prime-field arithmetic for the native (host, exact) oracle path.

These are the scalar types the reference uses throughout its native twins
(halo2curves ``bn256::Fr`` and ``secp256k1::{Fp, Fq}``; see e.g.
``eigentrust-zk/src/circuits/dynamic_sets/native.rs`` and
``eigentrust-zk/src/ecdsa/native.rs`` in the reference tree). The TPU path
never touches these classes — it works on limb-decomposed integer arrays
(``protocol_tpu.ops.limb``) or floats; these exist so the exact semantics
(field normalization via modular inverse, conservation checks, witness
values) have a fast-enough, obviously-correct host implementation.

Elements are immutable wrappers around a Python int in ``[0, MODULUS)``.
"""

from __future__ import annotations

import secrets

# BN254 (alt_bn128) scalar field r and base field q.
BN254_FR_MODULUS = (
    21888242871839275222246405745257275088548364400416034343698204186575808495617
)
BN254_FQ_MODULUS = (
    21888242871839275222246405745257275088696311157297823662689037894645226208583
)
# secp256k1 base field p and group order n.
SECP256K1_P = 2**256 - 2**32 - 977
SECP256K1_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


class FieldElement:
    """An element of a prime field; subclasses fix ``MODULUS``."""

    __slots__ = ("v",)
    MODULUS: int = 0

    def __init__(self, v: int = 0):
        self.v = v % self.MODULUS

    # --- constructors -----------------------------------------------------
    @classmethod
    def zero(cls):
        return cls(0)

    @classmethod
    def one(cls):
        return cls(1)

    @classmethod
    def random(cls):
        return cls(secrets.randbelow(cls.MODULUS))

    @classmethod
    def from_bytes_le(cls, data: bytes) -> "FieldElement":
        """Strict little-endian decode; value must be canonical (< MODULUS)."""
        v = int.from_bytes(data, "little")
        if v >= cls.MODULUS:
            raise ValueError("non-canonical field encoding")
        return cls(v)

    @classmethod
    def from_uniform_bytes_le(cls, data: bytes) -> "FieldElement":
        """Uniform reduction of up to 64 little-endian bytes (wide reduce).

        Matches halo2's ``from_uniform_bytes`` used by the reference for
        address/message embedding (``ecdsa/native.rs`` ``to_address``,
        ``eigentrust/src/attestation.rs`` ``to_attestation_fr``).
        """
        return cls(int.from_bytes(data, "little"))

    # --- arithmetic -------------------------------------------------------
    def __add__(self, other):
        return type(self)(self.v + other.v)

    def __sub__(self, other):
        return type(self)(self.v - other.v)

    def __mul__(self, other):
        return type(self)(self.v * other.v)

    def __neg__(self):
        return type(self)(-self.v)

    def __pow__(self, e: int):
        return type(self)(pow(self.v, e, self.MODULUS))

    def invert(self) -> "FieldElement":
        """Multiplicative inverse; raises ZeroDivisionError on zero."""
        if self.v == 0:
            raise ZeroDivisionError("zero has no inverse")
        return type(self)(pow(self.v, -1, self.MODULUS))

    def invert_or_zero(self) -> "FieldElement":
        """``invert().unwrap_or(ZERO)`` as used by the reference's field
        row-normalization (``dynamic_sets/native.rs`` converge)."""
        if self.v == 0:
            return type(self)(0)
        return self.invert()

    def sqrt(self):
        """Square root (Tonelli–Shanks); returns None if non-residue."""
        p = self.MODULUS
        v = self.v
        if v == 0:
            return type(self)(0)
        if p % 4 == 3:
            r = pow(v, (p + 1) // 4, p)
            return type(self)(r) if (r * r) % p == v else None
        if pow(v, (p - 1) // 2, p) != 1:
            return None
        # Tonelli–Shanks for p ≡ 1 (mod 4)
        q, s = p - 1, 0
        while q % 2 == 0:
            q //= 2
            s += 1
        z = 2
        while pow(z, (p - 1) // 2, p) != p - 1:
            z += 1
        m, c, t, r = s, pow(z, q, p), pow(v, q, p), pow(v, (q + 1) // 2, p)
        while t != 1:
            i, t2 = 0, t
            while t2 != 1:
                t2 = (t2 * t2) % p
                i += 1
            b = pow(c, 1 << (m - i - 1), p)
            m, c = i, (b * b) % p
            t, r = (t * c) % p, (r * b) % p
        return type(self)(r)

    # --- predicates / conversions ----------------------------------------
    def is_zero(self) -> bool:
        return self.v == 0

    def is_odd(self) -> bool:
        return self.v & 1 == 1

    def to_bytes_le(self, length: int = 32) -> bytes:
        return self.v.to_bytes(length, "little")

    def to_bytes_be(self, length: int = 32) -> bytes:
        return self.v.to_bytes(length, "big")

    def __int__(self):
        return self.v

    def __index__(self):
        return self.v

    def __eq__(self, other):
        return type(self) is type(other) and self.v == other.v

    def __hash__(self):
        return hash((self.MODULUS, self.v))

    def __repr__(self):
        return f"{type(self).__name__}(0x{self.v:x})"


_field_cache: dict = {}


def make_field(modulus: int, name: str) -> type:
    """Create (and cache) a FieldElement subclass for ``modulus``."""
    key = (modulus, name)
    if key not in _field_cache:
        _field_cache[key] = type(name, (FieldElement,), {"MODULUS": modulus})
    return _field_cache[key]


class Fr(FieldElement):
    """BN254 scalar field — the reference's native field ``N`` everywhere."""

    MODULUS = BN254_FR_MODULUS


class SecpBase(FieldElement):
    """secp256k1 base field Fp (curve coordinates)."""

    MODULUS = SECP256K1_P


class SecpScalar(FieldElement):
    """secp256k1 scalar field Fq (ECDSA signatures)."""

    MODULUS = SECP256K1_N
