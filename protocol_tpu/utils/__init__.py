"""Host-exact building blocks: prime fields, keccak-256, error types."""

from .fields import (
    BN254_FR_MODULUS,
    BN254_FQ_MODULUS,
    SECP256K1_P,
    SECP256K1_N,
    FieldElement,
    Fr,
    SecpBase,
    SecpScalar,
    make_field,
)
from .keccak import keccak256
from .errors import EigenError

__all__ = [
    "BN254_FR_MODULUS",
    "BN254_FQ_MODULUS",
    "SECP256K1_P",
    "SECP256K1_N",
    "FieldElement",
    "Fr",
    "SecpBase",
    "SecpScalar",
    "make_field",
    "keccak256",
    "EigenError",
]
