"""In-repo mock JSON-RPC devnet for the AttestationStation flow.

The reference integration-tests its client against a real Anvil devnet
spawned per test (``eigentrust/src/lib.rs:695-788``). This environment
has no EVM node, so this module provides the devnet stand-in the
VERDICT asked for: a threaded stdlib HTTP server speaking enough of the
Ethereum JSON-RPC surface for the full deploy → attest → logs → scores
round trip:

- ``eth_chainId`` / ``eth_blockNumber`` / ``eth_gasPrice`` /
  ``eth_getTransactionCount`` / ``eth_getTransactionReceipt``
- ``eth_sendRawTransaction``: decodes the EIP-155 legacy RLP
  transaction, RECOVERS THE SENDER from the signature (the part a
  codec-level test can't exercise), and executes it: contract-creation
  transactions register an AttestationStation instance at the EVM
  create address; calls to a registered instance decode the
  ``attest((address,bytes32,bytes)[])`` calldata and append logs.
- ``eth_getLogs`` / ``eth_call`` (the ``attestations`` getter).

Both contract families EXECUTE real code (r5; previously the station
was modeled):

- a creation transaction carrying the vendored AttestationStation
  creation bytecode deploys through the in-repo EVM **bytecode**
  interpreter (``client/evm.py``): the constructor runs, attest txs
  run the real calldata decoder/storage writes/LOG4 emission on the
  wire bytes, and ``eth_call`` executes the real public-mapping
  getter — the loop the reference gets from Anvil + real bytecode
  (``eigentrust/src/lib.rs:695-788``). Equivalence with the modeled
  ``LocalChain`` semantics is asserted in ``tests/test_evm_exec.py``;
  any OTHER non-Yul creation data still registers a modeled
  ``LocalChain`` (documented fallback for protocol-level tests).
- a creation transaction whose data is Yul source (the
  ``object "PlonkVerifier"`` artifact from ``zk/evm.py``) registers a
  contract whose ``eth_call``/``eth_estimateGas`` run the code through
  the in-repo Yul EVM (``zk/yul.py``, yellow-paper gas schedule) — the
  proof artifact is verified *on-chain over JSON-RPC*, not by a
  library call (``eigentrust-zk/src/verifier/mod.rs:148-168``).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..crypto.secp256k1 import Signature, recover_public_key
from ..utils.keccak import keccak256
from .att_station_bytecode import creation_bytecode
from .chain import (
    ATTEST_SELECTOR,
    ATTESTATIONS_SELECTOR,
    EVENT_TOPIC,
    ExecutedChain,
    LocalChain,
)
from .eth import address_from_public_key, rlp_encode

YUL_CREATION_MARKER = b'object "PlonkVerifier"'


class YulContract:
    """A deployed generated verifier: calls execute in the in-repo EVM."""

    def __init__(self, source: str):
        self.source = source

    def call(self, calldata: bytes) -> bytes:
        from ..zk.yul import YulVM  # VMRevert propagates to the RPC error

        out, _gas = YulVM(self.source).run(calldata)
        return out

    def estimate_gas(self, calldata: bytes) -> int:
        from ..zk.yul import YulVM

        _out, gas = YulVM(self.source).run_tx(calldata)
        return gas


def _rlp_decode(data: bytes):
    """Minimal RLP decoder (bytes + lists), returns (item, rest)."""
    if not data:
        raise ValueError("empty rlp")
    b0 = data[0]
    if b0 < 0x80:
        return data[:1], data[1:]
    if b0 < 0xB8:
        ln = b0 - 0x80
        return data[1 : 1 + ln], data[1 + ln :]
    if b0 < 0xC0:
        lln = b0 - 0xB7
        ln = int.from_bytes(data[1 : 1 + lln], "big")
        return data[1 + lln : 1 + lln + ln], data[1 + lln + ln :]
    if b0 < 0xF8:
        ln = b0 - 0xC0
        payload = data[1 : 1 + ln]
        rest = data[1 + ln :]
    else:
        lln = b0 - 0xF7
        ln = int.from_bytes(data[1 : 1 + lln], "big")
        payload = data[1 + lln : 1 + lln + ln]
        rest = data[1 + lln + ln :]
    items = []
    while payload:
        item, payload = _rlp_decode(payload)
        items.append(item)
    return items, rest


def _decode_attest_calldata(data: bytes) -> list:
    """Inverse of ``abi_encode_attest``: [(about, key, val)]."""
    assert data[:4] == ATTEST_SELECTOR
    body = data[4:]
    array_off = int.from_bytes(body[:32], "big")
    arr = body[array_off:]
    count = int.from_bytes(arr[:32], "big")
    entries = []
    for i in range(count):
        off = int.from_bytes(arr[32 + 32 * i : 64 + 32 * i], "big")
        elem = arr[32 + off :]
        about = elem[12:32]
        key = elem[32:64]
        val_off = int.from_bytes(elem[64:96], "big")  # rel. tuple start
        val_len = int.from_bytes(elem[val_off : val_off + 32], "big")
        val = elem[val_off + 32 : val_off + 32 + val_len]
        entries.append((about, key, val))
    return entries


class MockNode:
    """Threaded mock devnet; start() returns the node URL."""

    def __init__(self, chain_id: int = 31337):
        self.chain_id = chain_id
        self.nonces: dict = {}
        self.contracts: dict = {}   # address bytes -> LocalChain
        self.receipts: dict = {}
        self.block = 0
        self._lock = threading.Lock()
        self._server = None
        self._thread = None

    # -- tx execution ------------------------------------------------------
    def _execute_raw_tx(self, raw: bytes) -> str:
        fields, rest = _rlp_decode(raw)
        if rest:
            raise ValueError("trailing tx bytes")
        nonce, gas_price, gas, to, value, data, v, r, s = fields
        nonce_i = int.from_bytes(nonce, "big")
        v_i = int.from_bytes(v, "big")
        rec_id = (v_i - 35 - self.chain_id * 2)
        if rec_id not in (0, 1):
            raise ValueError("bad EIP-155 v")
        sighash = keccak256(rlp_encode(
            [nonce, gas_price, gas, to, value, data, self.chain_id, 0, 0]))
        sig = Signature(int.from_bytes(r, "big"), int.from_bytes(s, "big"),
                        rec_id)
        sender_pk = recover_public_key(sig, int.from_bytes(sighash, "big"))
        sender = address_from_public_key(sender_pk)
        with self._lock:
            expected = self.nonces.get(sender, 0)
            if nonce_i != expected:
                raise ValueError(f"bad nonce {nonce_i}, expected {expected}")
            self.nonces[sender] = expected + 1
            self.block += 1
            txh = keccak256(raw)
            if len(to) == 0:
                # contract creation at keccak(rlp([sender, nonce]))[12:];
                # Yul-source data deploys an executed verifier contract,
                # anything else the modeled AttestationStation
                addr = keccak256(rlp_encode([sender, nonce_i]))[12:]
                if YUL_CREATION_MARKER in bytes(data):
                    self.contracts[addr] = YulContract(
                        bytes(data).decode("utf-8"))
                elif bytes(data) == creation_bytecode():
                    # the real artifact: run its constructor in the
                    # bytecode EVM and serve the executed contract
                    self.contracts[addr] = ExecutedChain()
                else:
                    self.contracts[addr] = LocalChain()
                self.receipts[txh] = {"contractAddress": "0x" + addr.hex(),
                                      "status": "0x1",
                                      "blockNumber": hex(self.block)}
            else:
                chain = self.contracts.get(bytes(to))
                if chain is None:
                    raise ValueError("no contract at target address")
                if isinstance(chain, YulContract):
                    raise ValueError(
                        "verifier contract is view-only; use eth_call")
                if isinstance(chain, ExecutedChain):
                    # executed station: the REAL solc decoder is
                    # authoritative on the wire calldata; the modeled
                    # decoder runs only afterwards for the tx digest
                    # (None if it cannot parse what the contract took)
                    try:
                        entries = _decode_attest_calldata(bytes(data))
                    except Exception:
                        entries = None
                    chain.attest_raw(sender, bytes(data), entries)
                else:
                    entries = _decode_attest_calldata(bytes(data))
                    chain.attest(sender, entries)
                self.receipts[txh] = {"contractAddress": None,
                                      "status": "0x1",
                                      "blockNumber": hex(self.block)}
            return "0x" + txh.hex()

    # -- rpc dispatch ------------------------------------------------------
    def handle(self, method: str, params: list):
        if method == "eth_chainId":
            return hex(self.chain_id)
        if method == "eth_blockNumber":
            return hex(self.block)
        if method == "eth_gasPrice":
            return hex(10**9)
        if method == "eth_getTransactionCount":
            addr = bytes.fromhex(params[0].removeprefix("0x"))
            return hex(self.nonces.get(addr, 0))
        if method == "eth_getTransactionReceipt":
            return self.receipts.get(
                bytes.fromhex(params[0].removeprefix("0x")))
        if method == "eth_sendRawTransaction":
            return self._execute_raw_tx(
                bytes.fromhex(params[0].removeprefix("0x")))
        if method == "eth_getLogs":
            q = params[0]
            addr = bytes.fromhex(q["address"].removeprefix("0x"))
            chain = self.contracts.get(addr)
            if chain is None:
                return []
            from_block = int(q.get("fromBlock", "0x0"), 16)
            out = []
            for log in chain.get_logs():
                if log.block_number < from_block:
                    continue
                out.append({
                    "address": q["address"],
                    "topics": [
                        EVENT_TOPIC,
                        "0x" + log.creator.rjust(32, b"\x00").hex(),
                        "0x" + log.about.rjust(32, b"\x00").hex(),
                        "0x" + log.key.hex(),
                    ],
                    "data": "0x" + (
                        (32).to_bytes(32, "big")
                        + len(log.val).to_bytes(32, "big")
                        + log.val + b"\x00" * (-len(log.val) % 32)
                    ).hex(),
                    "blockNumber": hex(log.block_number),
                })
            return out
        if method == "eth_call":
            call = params[0]
            addr = bytes.fromhex(call["to"].removeprefix("0x"))
            chain = self.contracts.get(addr)
            if chain is None:
                return "0x"
            data = bytes.fromhex(call["data"].removeprefix("0x"))
            if isinstance(chain, YulContract):
                from ..zk.yul import VMRevert

                try:
                    return "0x" + chain.call(data).hex()
                except VMRevert as e:
                    raise ValueError(f"execution reverted: {e}") from e
            if isinstance(chain, ExecutedChain):
                from .evm import EvmRevert

                # the snapshot/restore in call_raw writes storage:
                # serialize against concurrent attest txs
                with self._lock:
                    try:
                        return "0x" + chain.call_raw(data).hex()
                    except EvmRevert as e:
                        raise ValueError(
                            f"execution reverted: {e}") from e
            if data[:4] != ATTESTATIONS_SELECTOR:
                raise ValueError("unsupported call selector")
            creator = data[16:36]
            about = data[48:68]
            key = data[68:100]
            val = chain.get_attestation(creator, about, key)
            enc = ((32).to_bytes(32, "big")
                   + len(val).to_bytes(32, "big")
                   + val + b"\x00" * (-len(val) % 32))
            return "0x" + enc.hex()
        if method == "eth_estimateGas":
            call = params[0]
            addr = bytes.fromhex(call["to"].removeprefix("0x"))
            chain = self.contracts.get(addr)
            data = bytes.fromhex(call.get("data", "0x").removeprefix("0x"))
            if isinstance(chain, YulContract):
                from ..zk.yul import VMRevert

                try:
                    return hex(chain.estimate_gas(data))
                except VMRevert as e:
                    raise ValueError(f"execution reverted: {e}") from e
            return hex(100_000)
        raise ValueError(f"unsupported method {method}")

    # -- http --------------------------------------------------------------
    def start(self) -> str:
        node = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length))
                try:
                    result = node.handle(req["method"], req.get("params", []))
                    reply = {"jsonrpc": "2.0", "id": req.get("id"),
                             "result": result}
                except Exception as e:  # noqa: BLE001 - devnet surface
                    reply = {"jsonrpc": "2.0", "id": req.get("id"),
                             "error": {"code": -32000, "message": str(e)}}
                body = json.dumps(reply).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="mocknode-http", daemon=True)
        self._thread.start()
        return f"http://127.0.0.1:{self._server.server_address[1]}"

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server.server_close()
