"""Circuit IO types: scores and public-input bundles with byte codecs.

Mirrors ``eigentrust/src/circuit.rs``: ``Score`` (address + three score
encodings), ``ETSetup``/``ETPublicInputs`` (layout: participants ‖ scores
‖ domain ‖ opinion_hash, 32-byte LE field encodings), ``ThSetup``/
``ThPublicInputs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction

from ..utils.errors import EigenError
from ..utils.fields import Fr


@dataclass
class Score:
    """One peer's score in all encodings (circuit.rs:47-56)."""

    address: bytes  # 20 bytes
    score_fr: bytes  # 32 bytes, big-endian (reference reverses LE repr)
    numerator: int
    denominator: int

    @property
    def score_int(self) -> int:
        return self.numerator // self.denominator

    @property
    def ratio(self) -> Fraction:
        return Fraction(self.numerator, self.denominator)


@dataclass
class ETPublicInputs:
    """EigenTrust circuit public inputs (circuit.rs:84-151)."""

    participants: list  # [Fr] length num_neighbours
    scores: list  # [Fr]
    domain: Fr
    opinion_hash: Fr

    def to_flat(self) -> list:
        return [*self.participants, *self.scores, self.domain, self.opinion_hash]

    def to_bytes(self) -> bytes:
        return b"".join(x.to_bytes_le() for x in self.to_flat())

    @classmethod
    def from_bytes(cls, data: bytes, num_neighbours: int) -> "ETPublicInputs":
        expected = (2 * num_neighbours + 2) * 32
        if len(data) != expected:
            raise EigenError(
                "parsing_error", f"expected {expected} bytes, got {len(data)}"
            )
        vals = [Fr.from_bytes_le(data[i : i + 32]) for i in range(0, len(data), 32)]
        return cls(
            participants=vals[:num_neighbours],
            scores=vals[num_neighbours : 2 * num_neighbours],
            domain=vals[2 * num_neighbours],
            opinion_hash=vals[2 * num_neighbours + 1],
        )


@dataclass
class ETSetup:
    """Everything et_circuit_setup produces (circuit.rs ETSetup)."""

    address_set: list  # [bytes20]
    attestation_matrix: list  # [[SignedAttestation | None]]
    pub_keys: list  # [PublicKey | None]
    pub_inputs: ETPublicInputs
    rational_scores: list  # [Fraction]
    # (matrix, valid): filtered opinion rows as plain ints + slot mask —
    # the hand-off to ConvergeBackend, computed once during setup.
    opinion: tuple = None


@dataclass
class ThPublicInputs:
    """Threshold circuit public inputs (circuit.rs:153-236):
    address ‖ threshold ‖ th_check-bit ‖ aggregator instances."""

    address: Fr
    threshold: Fr
    threshold_check: bool
    agg_instances: list = field(default_factory=list)

    def to_flat(self) -> list:
        return [
            self.address,
            self.threshold,
            Fr(1 if self.threshold_check else 0),
            *self.agg_instances,
        ]

    def to_bytes(self) -> bytes:
        return b"".join(x.to_bytes_le() for x in self.to_flat())

    @classmethod
    def from_bytes(cls, data: bytes) -> "ThPublicInputs":
        if len(data) % 32 != 0 or len(data) < 96:
            raise EigenError("parsing_error", "bad threshold public-input bytes")
        vals = [Fr.from_bytes_le(data[i : i + 32]) for i in range(0, len(data), 32)]
        return cls(
            address=vals[0],
            threshold=vals[1],
            threshold_check=not vals[2].is_zero(),
            agg_instances=vals[3:],
        )


@dataclass
class ThSetup:
    """Threshold circuit setup bundle. ``et_setup``/``ratio`` carry the
    EigenTrust context the prover needs to re-prove and aggregate the
    inner snark (the reference's th_circuit_setup holds the same data
    live while it builds the Snark, lib.rs:469-534)."""

    pub_inputs: ThPublicInputs
    num_decomposed: list  # [Fr] decimal limbs
    den_decomposed: list  # [Fr]
    et_setup: "ETSetup" = None
    ratio: Fraction = None
