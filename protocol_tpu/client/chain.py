"""Chain access: the AttestationStation contract surface.

Two implementations of one interface (the reference binds the real
contract via ethers-rs abigen, ``eigentrust/src/att_station.rs``):

- :class:`LocalChain` — in-process simulation of the AttestationStation
  semantics (attestations mapping + AttestationCreated logs). This is the
  framework's fast "fake backend" for tests and local development; the
  reference's equivalent is spawning a real Anvil devnet per test
  (SURVEY.md §4 layer 5).
- :class:`RpcChain` — a JSON-RPC client (eth_getLogs / raw-tx submission)
  speaking to a real node, with hand-rolled ABI coding for
  ``attest((address,bytes32,bytes)[])`` and the
  ``AttestationCreated(address,address,bytes32,bytes)`` event.
"""

from __future__ import annotations

import json
import time
import urllib.request
from dataclasses import dataclass

from ..utils.errors import EigenError
from ..utils.keccak import keccak256

# event AttestationCreated(address indexed creator, address indexed about,
#                          bytes32 indexed key, bytes val)
EVENT_SIGNATURE = "AttestationCreated(address,address,bytes32,bytes)"
EVENT_TOPIC = "0x" + keccak256(EVENT_SIGNATURE.encode()).hex()
ATTEST_SELECTOR = keccak256(b"attest((address,bytes32,bytes)[])")[:4]
ATTESTATIONS_SELECTOR = keccak256(
    b"attestations(address,address,bytes32)")[:4]


def _await_deploy_receipt(rpc, txh: str, created: bytes,
                          receipt_timeout: float = 120.0) -> None:
    """Poll for a contract-creation receipt and validate it.

    Without this, a rejected creation surfaces much later as reads
    against a missing contract (eth_call returns 0x — e.g. a valid
    proof misreported as rejected). Real nodes return null until the
    tx is mined — poll up to receipt_timeout (default covers several
    ~12 s blocks; raise it for congested networks); the mock devnet
    mines synchronously, so the first poll hits. A timeout is reported
    as 'possibly still pending', distinct from an executed-and-failed
    (status != 0x1) deploy, so callers don't blindly re-deploy and pay
    gas twice."""
    deadline = time.monotonic() + receipt_timeout
    while True:
        receipt = rpc("eth_getTransactionReceipt", [txh])
        if receipt or time.monotonic() >= deadline:
            break
        time.sleep(min(2.0, max(0.1, receipt_timeout / 60)))
    if not receipt:
        raise EigenError(
            "transaction_error",
            f"no deploy receipt for {txh} after {receipt_timeout:.0f}s; "
            "the creation tx may still be pending — do not re-send "
            "without checking the nonce")
    if receipt.get("status") != "0x1":
        raise EigenError(
            "transaction_error",
            f"contract deploy reverted (receipt={receipt!r})")
    got = receipt.get("contractAddress")
    if got and bytes.fromhex(got.removeprefix("0x")) != created:
        raise EigenError(
            "transaction_error",
            f"deploy address mismatch: {got} != 0x{created.hex()}")


@dataclass
class AttestationLog:
    """One decoded AttestationCreated event."""

    creator: bytes  # 20
    about: bytes  # 20
    key: bytes  # 32
    val: bytes
    block_number: int = 0


class AttestationStation:
    """Interface both chains implement."""

    def attest(self, creator: bytes, entries: list) -> str:
        """entries: [(about20, key32, payload_bytes)]; returns tx hash."""
        raise NotImplementedError

    def get_attestation(self, creator: bytes, about: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def get_logs(self, from_block: int = 0) -> list:
        raise NotImplementedError


class LocalChain(AttestationStation):
    """In-memory AttestationStation with contract-equivalent semantics."""

    def __init__(self):
        self.store: dict = {}  # (creator, about, key) -> val
        self.logs: list = []
        self.block = 0

    def attest(self, creator: bytes, entries: list) -> str:
        self.block += 1
        for about, key, val in entries:
            self.store[(creator, about, key)] = val
            self.logs.append(
                AttestationLog(creator, about, key, val, self.block)
            )
        digest = keccak256(
            creator + b"".join(a + k + v for a, k, v in entries)
        )
        return "0x" + digest.hex()

    def get_attestation(self, creator: bytes, about: bytes, key: bytes) -> bytes:
        return self.store.get((creator, about, key), b"")

    def get_logs(self, from_block: int = 0) -> list:
        return [log for log in self.logs if log.block_number >= from_block]

    # -- persistence (lets the CLI run a durable local chain without a
    # node; the reference's equivalent is an external Anvil devnet) -------
    def to_json(self) -> dict:
        return {
            "block": self.block,
            "logs": [
                {
                    "creator": log.creator.hex(),
                    "about": log.about.hex(),
                    "key": log.key.hex(),
                    "val": log.val.hex(),
                    "block_number": log.block_number,
                }
                for log in self.logs
            ],
        }

    @classmethod
    def from_json(cls, data: dict) -> "LocalChain":
        chain = cls()
        chain.block = data.get("block", 0)
        for row in data.get("logs", []):
            log = AttestationLog(
                creator=bytes.fromhex(row["creator"]),
                about=bytes.fromhex(row["about"]),
                key=bytes.fromhex(row["key"]),
                val=bytes.fromhex(row["val"]),
                block_number=row["block_number"],
            )
            chain.logs.append(log)
            chain.store[(log.creator, log.about, log.key)] = log.val
        return chain


class ExecutedChain(AttestationStation):
    """AttestationStation backed by the REAL vendored contract bytecode
    running in the in-repo EVM (``client/evm.py``) — the executed twin
    of ``LocalChain``'s modeled semantics.

    Deploy runs the actual creation code (constructor included);
    ``attest`` executes the runtime's calldata decoder, storage writes
    and LOG4 emission; ``get_attestation`` executes the public-mapping
    getter. The reference gets this loop from Anvil + real bytecode
    (``eigentrust/src/lib.rs:695-788``); here the devnet's contract
    registry instantiates THIS class, so a codec or semantic divergence
    between the Python model and the real contract surfaces as a test
    failure (``tests/test_evm_exec.py`` asserts LocalChain equivalence
    tx for tx)."""

    def __init__(self):
        from .att_station_bytecode import creation_bytecode
        from .evm import Evm

        # devnet account: a fixed self address (the EVM only exposes it
        # through ADDRESS, which the contract does not read)
        self.evm = Evm.deploy(creation_bytecode(),
                              caller=b"\x00" * 20,
                              address=b"\xa7" * 20)
        self.logs: list = []
        self.block = 0
        self.gas_used = 0       # tx gas (attest executions)
        self.view_gas_used = 0  # eth_call gas (state discarded)

    def attest(self, creator: bytes, entries: list) -> str:
        return self.attest_raw(creator, abi_encode_attest(entries),
                               entries)

    def attest_raw(self, creator: bytes, calldata: bytes,
                   entries: list | None) -> str:
        """Execute an attest with the CALLER'S raw calldata — the
        devnet path, so the real contract's calldata decoder sees the
        exact wire bytes (not a re-encoding). ``entries`` feeds only
        the tx digest (LocalChain hash parity); pass None when the
        modeled decoder cannot parse what the real contract accepted —
        the digest then covers the raw calldata."""
        from .evm import EvmRevert

        self.block += 1
        try:
            _, gas, logs = self.evm.call(creator, calldata)
        except EvmRevert as e:
            raise EigenError(
                "transaction_error",
                f"attest reverted: {e.data.hex() or e}") from e
        self.gas_used += gas
        for log in logs:
            if log.topics[0] != int(EVENT_TOPIC, 16):
                continue
            # AttestationCreated(indexed creator, indexed about,
            # indexed key, bytes val): val is ABI-encoded in data
            off = int.from_bytes(log.data[:32], "big")
            ln = int.from_bytes(log.data[off:off + 32], "big")
            val = log.data[off + 32:off + 32 + ln]
            self.logs.append(AttestationLog(
                creator=log.topics[1].to_bytes(32, "big")[12:],
                about=log.topics[2].to_bytes(32, "big")[12:],
                key=log.topics[3].to_bytes(32, "big"),
                val=val,
                block_number=self.block,
            ))
        if entries is None:
            digest = keccak256(creator + calldata)
        else:
            digest = keccak256(
                creator + b"".join(a + k + v for a, k, v in entries))
        return "0x" + digest.hex()

    def get_attestation(self, creator: bytes, about: bytes,
                        key: bytes) -> bytes:
        data = (ATTESTATIONS_SELECTOR + _pad32(b"\x00" * 12 + creator)
                + _pad32(b"\x00" * 12 + about) + key)
        return abi_decode_bytes(self.call_raw(data))

    def call_raw(self, calldata: bytes) -> bytes:
        """eth_call against the executed contract: raw calldata in,
        raw ABI return out. eth_call semantics: state changes are
        DISCARDED (storage snapshot/restore), so a mutating simulation
        can never desync the getter from the event log. View gas is
        tracked separately — it is not transaction gas. NOT
        thread-safe against concurrent attests (the snapshot/restore
        writes storage): the devnet serializes through MockNode's
        lock."""
        snapshot = dict(self.evm.storage)
        try:
            ret, gas, _ = self.evm.call(b"\x00" * 20, calldata)
        finally:
            self.evm.storage = snapshot
        self.view_gas_used += gas
        return ret

    def get_logs(self, from_block: int = 0) -> list:
        return [log for log in self.logs
                if log.block_number >= from_block]


# --- minimal ABI coding ---------------------------------------------------


def _pad32(data: bytes) -> bytes:
    return data + b"\x00" * (-len(data) % 32)


def _uint(v: int) -> bytes:
    return v.to_bytes(32, "big")


def abi_encode_attest(entries: list) -> bytes:
    """Calldata for attest((address,bytes32,bytes)[])."""
    # each element tuple is dynamic (contains bytes) → array stores offsets
    elements = []
    for about, key, val in entries:
        # tuple head: about, key, offset-of-val (3 words); tail: len + data
        elem = (
            _pad32(b"\x00" * 12 + about)
            + key
            + _uint(3 * 32)
            + _uint(len(val))
            + _pad32(val)
        )
        elements.append(elem)
    heads = []
    offset = 32 * len(elements)
    for elem in elements:
        heads.append(_uint(offset))
        offset += len(elem)
    array = _uint(len(elements)) + b"".join(heads) + b"".join(elements)
    return ATTEST_SELECTOR + _uint(32) + array


def abi_decode_bytes(data: bytes) -> bytes:
    """Decode a single dynamic `bytes` return/data value."""
    if len(data) < 64:
        raise EigenError("parsing_error", "short ABI bytes payload")
    offset = int.from_bytes(data[:32], "big")
    length = int.from_bytes(data[offset : offset + 32], "big")
    return data[offset + 32 : offset + 32 + length]


class RpcChain(AttestationStation):
    """JSON-RPC AttestationStation client (HTTP, stdlib only)."""

    def __init__(self, node_url: str, contract_address: bytes, chain_id: int = 31337):
        self.node_url = node_url
        self.contract_address = contract_address
        self.chain_id = chain_id
        self._id = 0

    # -- raw rpc -----------------------------------------------------------
    def rpc(self, method: str, params: list):
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        req = urllib.request.Request(
            self.node_url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                reply = json.loads(resp.read())
        except OSError as e:
            raise EigenError("connection_error", str(e)) from e
        if "error" in reply:
            raise EigenError("network_error", str(reply["error"]))
        return reply["result"]

    # -- AttestationStation surface ---------------------------------------
    def attest_signed(self, keypair, entries: list) -> str:
        """Sign and submit an attest() call from `keypair`."""
        from .eth import address_from_public_key, sign_legacy_tx

        sender = "0x" + address_from_public_key(keypair.public_key).hex()
        nonce = int(self.rpc("eth_getTransactionCount", [sender, "pending"]), 16)
        gas_price = int(self.rpc("eth_gasPrice", []), 16)
        raw = sign_legacy_tx(
            keypair,
            nonce=nonce,
            gas_price=gas_price,
            gas=2_000_000,
            to=self.contract_address,
            value=0,
            data=abi_encode_attest(entries),
            chain_id=self.chain_id,
        )
        return self.rpc("eth_sendRawTransaction", ["0x" + raw.hex()])

    def attest(self, creator: bytes, entries: list) -> str:
        raise EigenError(
            "keys_error",
            "RpcChain needs a signing key; use attest_signed(keypair, entries)",
        )

    @classmethod
    def deploy_signed(cls, node_url: str, keypair, chain_id: int = 31337,
                      gas: int = 2_000_000) -> "RpcChain":
        """Deploy the AttestationStation contract (the vendored creation
        bytecode, ``att_station_bytecode.py``) from ``keypair`` and
        return an RpcChain bound to the created address — the
        reference's ``deploy_as`` (``eigentrust/src/eth.rs:18-25``).

        The created address is derived the EVM way:
        keccak256(rlp([sender, nonce]))[12:]."""
        from .att_station_bytecode import creation_bytecode
        from .eth import address_from_public_key, rlp_encode, sign_legacy_tx

        chain = cls(node_url, b"\x00" * 20, chain_id)
        sender_b = address_from_public_key(keypair.public_key)
        sender = "0x" + sender_b.hex()
        nonce = int(chain.rpc("eth_getTransactionCount",
                              [sender, "pending"]), 16)
        gas_price = int(chain.rpc("eth_gasPrice", []), 16)
        raw = sign_legacy_tx(
            keypair,
            nonce=nonce,
            gas_price=gas_price,
            gas=gas,
            to=b"",  # contract creation
            value=0,
            data=creation_bytecode(),
            chain_id=chain_id,
        )
        txh = chain.rpc("eth_sendRawTransaction", ["0x" + raw.hex()])
        created = keccak256(rlp_encode([sender_b, nonce]))[12:]
        _await_deploy_receipt(chain.rpc, txh, created)
        chain.contract_address = created
        return chain

    def get_attestation(self, creator: bytes, about: bytes, key: bytes) -> bytes:
        data = (ATTESTATIONS_SELECTOR + _pad32(b"\x00" * 12 + creator)
                + _pad32(b"\x00" * 12 + about) + key)
        result = self.rpc(
            "eth_call",
            [{"to": "0x" + self.contract_address.hex(), "data": "0x" + data.hex()}, "latest"],
        )
        return abi_decode_bytes(bytes.fromhex(result.removeprefix("0x")))

    def get_logs(self, from_block: int = 0) -> list:
        raw_logs = self.rpc(
            "eth_getLogs",
            [
                {
                    "fromBlock": hex(from_block),
                    "toBlock": "latest",
                    "address": "0x" + self.contract_address.hex(),
                    "topics": [EVENT_TOPIC],
                }
            ],
        )
        out = []
        for log in raw_logs:
            topics = log["topics"]
            data = bytes.fromhex(log["data"].removeprefix("0x"))
            out.append(
                AttestationLog(
                    creator=bytes.fromhex(topics[1].removeprefix("0x"))[-20:],
                    about=bytes.fromhex(topics[2].removeprefix("0x"))[-20:],
                    key=bytes.fromhex(topics[3].removeprefix("0x")),
                    val=abi_decode_bytes(data),
                    block_number=int(log["blockNumber"], 16),
                )
            )
        return out


class VerifierContract:
    """A deployed generated PLONK verifier, driven over JSON-RPC.

    Twin of the reference's on-chain verifier flow: the Yul artifact
    from ``zk/evm.py`` is deployed as a contract-creation transaction
    and proofs are checked with ``eth_call`` (gas via
    ``eth_estimateGas``) — the devnet executes the code in the in-repo
    EVM (``client/mocknode.py``), so a codegen or calldata-layout bug
    surfaces as an on-chain revert, not a Python library disagreement.
    Reference anchor: eigentrust-zk/src/verifier/mod.rs:148-168 (deploy
    + call against an in-memory EVM)."""

    def __init__(self, node_url: str, address: bytes, chain_id: int = 31337):
        self.node_url = node_url
        self.address = address
        self.chain_id = chain_id
        self._id = 0

    rpc = RpcChain.rpc  # same JSON-RPC plumbing

    @classmethod
    def deploy_signed(cls, node_url: str, keypair, yul_source: str,
                      chain_id: int = 31337, gas: int = 10_000_000,
                      receipt_timeout: float = 120.0) -> "VerifierContract":
        from .eth import address_from_public_key, rlp_encode, sign_legacy_tx

        probe = cls(node_url, b"\x00" * 20, chain_id)
        sender_b = address_from_public_key(keypair.public_key)
        nonce = int(probe.rpc("eth_getTransactionCount",
                              ["0x" + sender_b.hex(), "pending"]), 16)
        gas_price = int(probe.rpc("eth_gasPrice", []), 16)
        raw = sign_legacy_tx(
            keypair, nonce=nonce, gas_price=gas_price, gas=gas,
            to=b"", value=0, data=yul_source.encode("utf-8"),
            chain_id=chain_id,
        )
        txh = probe.rpc("eth_sendRawTransaction", ["0x" + raw.hex()])
        created = keccak256(rlp_encode([sender_b, nonce]))[12:]
        _await_deploy_receipt(probe.rpc, txh, created, receipt_timeout)
        return cls(node_url, created, chain_id)

    def verify(self, calldata: bytes) -> bool:
        """eth_call the verifier; reverts (RPC errors) read as reject."""
        try:
            result = self.rpc("eth_call", [
                {"to": "0x" + self.address.hex(),
                 "data": "0x" + calldata.hex()}, "latest"])
        except EigenError:
            return False
        out = bytes.fromhex(result.removeprefix("0x"))
        return len(out) == 32 and int.from_bytes(out, "big") == 1

    def estimate_gas(self, calldata: bytes) -> int:
        return int(self.rpc("eth_estimateGas", [
            {"to": "0x" + self.address.hex(),
             "data": "0x" + calldata.hex()}]), 16)
