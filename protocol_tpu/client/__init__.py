"""Client SDK: attestation codecs, storage, eth utils, chain access, and
the Client facade (reference: the ``eigentrust`` crate)."""

from .attestation import (
    DOMAIN_PREFIX,
    AttestationData,
    SignatureData,
    SignedAttestationData,
)
from .storage import (
    AttestationRecord,
    BinFileStorage,
    CSVFileStorage,
    JSONFileStorage,
    ScoreRecord,
    Storage,
)
from .eth import (
    address_from_public_key,
    ecdsa_keypairs_from_mnemonic,
    scalar_from_address,
)
from .chain import AttestationStation, LocalChain, RpcChain
from .circuit_io import ETPublicInputs, ETSetup, Score, ThPublicInputs, ThSetup
from .client import Client, ClientConfig

__all__ = [
    "DOMAIN_PREFIX",
    "AttestationData",
    "SignatureData",
    "SignedAttestationData",
    "AttestationRecord",
    "BinFileStorage",
    "CSVFileStorage",
    "JSONFileStorage",
    "ScoreRecord",
    "Storage",
    "address_from_public_key",
    "ecdsa_keypairs_from_mnemonic",
    "scalar_from_address",
    "AttestationStation",
    "LocalChain",
    "RpcChain",
    "ETPublicInputs",
    "ETSetup",
    "Score",
    "ThPublicInputs",
    "ThSetup",
    "Client",
    "ClientConfig",
]
