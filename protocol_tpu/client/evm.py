"""EVM bytecode interpreter — executes vendored contract artifacts.

``zk/yul.py`` executes the GENERATED verifier from its Yul AST; this
module is the bytecode front-end the AttestationStation needs: the
vendored creation blob (``att_station_bytecode.py``, the same public
artifact the reference embeds and deploys against Anvil —
``eigentrust/src/att_station.rs:119``, driven by the integration flow
``eigentrust/src/lib.rs:695-788``) is REAL solc output, so the devnet
can now run the actual contract code for deploy/attest/read/logs
instead of modeling its semantics in Python (VERDICT r4 "missing #1").

Scope: a single-contract machine — the full Shanghai-era opcode set a
solc 0.8.x storage contract emits (stack/arith/bit ops, keccak,
memory, storage, flow, logs, calldata/code copies, environment),
without cross-contract CALL/CREATE (the AttestationStation makes
none; hitting one raises loudly rather than mis-executing).

Gas follows the same yellow-paper/post-Berlin discipline as the Yul
VM: per-opcode Appendix-G base costs, quadratic memory expansion,
keccak + copy word costs, EIP-2929 warm/cold storage access, and
EIP-2200 SSTORE set/reset pricing. EIP-3529 refunds are NOT modeled
(cleared slots charge the full reset cost) — devnet gas is therefore
an upper bound for delete-heavy flows. Equivalence with the modeled
``LocalChain`` is pinned by ``tests/test_evm_exec.py`` — same txs in,
same logs and getter bytes out.
"""

from __future__ import annotations

from ..utils.errors import EigenError
from ..utils.keccak import keccak256

WORD = (1 << 256) - 1
SIGN_BIT = 1 << 255

# Appendix-G base costs for every opcode this machine implements
_G_ZERO = ("STOP", "RETURN", "REVERT")
_G_BASE = ("ADDRESS", "ORIGIN", "CALLER", "CALLVALUE", "CALLDATASIZE",
           "CODESIZE", "GASPRICE", "COINBASE", "TIMESTAMP", "NUMBER",
           "PREVRANDAO", "GASLIMIT", "CHAINID", "RETURNDATASIZE",
           "POP", "PC", "MSIZE", "GAS", "BASEFEE", "PUSH0")
_G_VERYLOW = ("ADD", "SUB", "NOT", "LT", "GT", "SLT", "SGT", "EQ",
              "ISZERO", "AND", "OR", "XOR", "BYTE", "SHL", "SHR",
              "SAR", "CALLDATALOAD", "MLOAD", "MSTORE", "MSTORE8")
_G_LOW = ("MUL", "DIV", "SDIV", "MOD", "SMOD", "SIGNEXTEND",
          "SELFBALANCE")
_G_MID = ("ADDMOD", "MULMOD", "JUMP")
_G_HIGH = ("JUMPI",)


class EvmRevert(Exception):
    """REVERT (or an exceptional halt) — ``data`` is the revert payload
    (empty for invalid-opcode/stack/jump faults, per EVM semantics the
    whole tx's gas is NOT modeled for faults; the devnet treats any
    raise as tx failure)."""

    def __init__(self, data: bytes = b"", reason: str = "revert"):
        super().__init__(reason)
        self.data = data


class _Halt(Exception):
    def __init__(self, data: bytes):
        self.data = data


class EvmLog:
    __slots__ = ("address", "topics", "data")

    def __init__(self, address: bytes, topics: list, data: bytes):
        self.address = address
        self.topics = topics  # list of 32-byte values (ints)
        self.data = data


def _op_name(op: int) -> str:
    return _OPNAMES.get(op, f"0x{op:02x}")


_OPNAMES = {
    0x00: "STOP", 0x01: "ADD", 0x02: "MUL", 0x03: "SUB", 0x04: "DIV",
    0x05: "SDIV", 0x06: "MOD", 0x07: "SMOD", 0x08: "ADDMOD",
    0x09: "MULMOD", 0x0A: "EXP", 0x0B: "SIGNEXTEND",
    0x10: "LT", 0x11: "GT", 0x12: "SLT", 0x13: "SGT", 0x14: "EQ",
    0x15: "ISZERO", 0x16: "AND", 0x17: "OR", 0x18: "XOR", 0x19: "NOT",
    0x1A: "BYTE", 0x1B: "SHL", 0x1C: "SHR", 0x1D: "SAR",
    0x20: "KECCAK256",
    0x30: "ADDRESS", 0x32: "ORIGIN", 0x33: "CALLER", 0x34: "CALLVALUE",
    0x35: "CALLDATALOAD", 0x36: "CALLDATASIZE", 0x37: "CALLDATACOPY",
    0x38: "CODESIZE", 0x39: "CODECOPY", 0x3A: "GASPRICE",
    0x3D: "RETURNDATASIZE", 0x3E: "RETURNDATACOPY",
    0x41: "COINBASE", 0x42: "TIMESTAMP", 0x43: "NUMBER",
    0x44: "PREVRANDAO", 0x45: "GASLIMIT", 0x46: "CHAINID",
    0x47: "SELFBALANCE", 0x48: "BASEFEE",
    0x50: "POP", 0x51: "MLOAD", 0x52: "MSTORE", 0x53: "MSTORE8",
    0x54: "SLOAD", 0x55: "SSTORE", 0x56: "JUMP", 0x57: "JUMPI",
    0x58: "PC", 0x59: "MSIZE", 0x5A: "GAS", 0x5B: "JUMPDEST",
    0x5F: "PUSH0",
    0xF3: "RETURN", 0xFD: "REVERT", 0xFE: "INVALID",
}
_BASE_GAS = {}
for _names, _cost in ((_G_ZERO, 0), (_G_BASE, 2), (_G_VERYLOW, 3),
                      (_G_LOW, 5), (_G_MID, 8), (_G_HIGH, 10)):
    for _n in _names:
        _BASE_GAS[_n] = _cost
_BASE_GAS.update({"KECCAK256": 30, "JUMPDEST": 1, "SLOAD": 0,
                  "SSTORE": 0, "EXP": 10, "CALLDATACOPY": 3,
                  "CODECOPY": 3, "RETURNDATACOPY": 3, "INVALID": 0})

_COLD_SLOAD = 2100  # EIP-2929
_WARM_ACCESS = 100
_SSTORE_SET = 20000  # EIP-2200 (on top of the cold/warm access cost)
_SSTORE_RESET = 2900
_LOG_BASE = 375
_LOG_TOPIC = 375
_LOG_DATA_BYTE = 8
_COPY_WORD = 3
_KECCAK_WORD = 6
_MEM_WORD = 3


def _signed(v: int) -> int:
    return v - (1 << 256) if v & SIGN_BIT else v


class Evm:
    """One contract account: runtime code + storage + gas meter."""

    def __init__(self, runtime: bytes, address: bytes):
        self.runtime = runtime
        self.address = address
        self.storage: dict = {}
        self.deploy_logs: list = []
        self._jumpdests = self._scan_jumpdests(runtime)

    # --- lifecycle --------------------------------------------------------

    @classmethod
    def deploy(cls, creation: bytes, caller: bytes, address: bytes,
               value: int = 0, calldata: bytes = b"") -> "Evm":
        """Run the creation code; its RETURN payload becomes the
        runtime. Constructor storage writes land on the new account."""
        contract = cls(b"", address)
        runtime, _gas, logs = contract._execute(
            creation, caller=caller, calldata=calldata, value=value,
            code_is_creation=True)
        if not runtime:
            raise EigenError("contract_error",
                             "creation code returned no runtime")
        contract.runtime = bytes(runtime)
        contract._jumpdests = cls._scan_jumpdests(contract.runtime)
        contract.deploy_logs = logs
        return contract

    def call(self, caller: bytes, calldata: bytes, value: int = 0):
        """One message call against the runtime code.

        Returns (return_data, gas_used, logs). Reverts raise
        ``EvmRevert`` with the payload."""
        return self._execute(self.runtime, caller=caller,
                             calldata=calldata, value=value)

    # --- interpreter ------------------------------------------------------

    @staticmethod
    def _scan_jumpdests(code: bytes) -> frozenset:
        dests = set()
        i = 0
        n = len(code)
        while i < n:
            op = code[i]
            if op == 0x5B:
                dests.add(i)
            if 0x60 <= op <= 0x7F:  # PUSH1..PUSH32 skip immediates
                i += op - 0x5F
            i += 1
        return frozenset(dests)

    def _execute(self, code: bytes, caller: bytes, calldata: bytes,
                 value: int, code_is_creation: bool = False):
        stack: list = []
        mem = bytearray()
        gas = 0
        mem_words_charged = 0
        logs: list = []
        warm_slots: set = set()
        returndata = b""
        jumpdests = (self._scan_jumpdests(code) if code_is_creation
                     else self._jumpdests)

        def fault(reason):
            raise EvmRevert(b"", reason)

        def pop():
            if not stack:
                fault("stack underflow")
            return stack.pop()

        def push(v):
            if len(stack) >= 1024:
                fault("stack overflow")
            stack.append(v & WORD)

        def charge_mem(offset, size):
            nonlocal gas, mem_words_charged
            if size == 0:
                return
            if offset + size > (1 << 32):
                fault("memory offset out of range")
            words = (offset + size + 31) // 32
            if words > mem_words_charged:
                gas += (_MEM_WORD * words + words * words // 512) - (
                    _MEM_WORD * mem_words_charged
                    + mem_words_charged * mem_words_charged // 512)
                mem_words_charged = words
            need = words * 32
            if len(mem) < need:
                mem.extend(b"\x00" * (need - len(mem)))

        def mread(offset, size):
            charge_mem(offset, size)
            return bytes(mem[offset:offset + size])

        def mwrite(offset, data):
            charge_mem(offset, len(data))
            mem[offset:offset + len(data)] = data

        pc = 0
        n = len(code)
        caller_int = int.from_bytes(caller, "big")
        addr_int = int.from_bytes(self.address, "big")
        try:
            while pc < n:
                op = code[pc]
                if 0x60 <= op <= 0x7F:  # PUSH1..PUSH32
                    width = op - 0x5F
                    push(int.from_bytes(code[pc + 1:pc + 1 + width],
                                        "big"))
                    gas += 3
                    pc += width + 1
                    continue
                if 0x80 <= op <= 0x8F:  # DUP1..DUP16
                    depth = op - 0x7F
                    if len(stack) < depth:
                        fault("stack underflow")
                    push(stack[-depth])
                    gas += 3
                    pc += 1
                    continue
                if 0x90 <= op <= 0x9F:  # SWAP1..SWAP16
                    depth = op - 0x8F
                    if len(stack) < depth + 1:
                        fault("stack underflow")
                    stack[-1], stack[-depth - 1] = (stack[-depth - 1],
                                                    stack[-1])
                    gas += 3
                    pc += 1
                    continue
                if 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                    ntopics = op - 0xA0
                    offset, size = pop(), pop()
                    topics = [pop() for _ in range(ntopics)]
                    data = mread(offset, size)
                    gas += (_LOG_BASE + _LOG_TOPIC * ntopics
                            + _LOG_DATA_BYTE * size)
                    logs.append(EvmLog(self.address, topics, data))
                    pc += 1
                    continue

                name = _op_name(op)
                gas += _BASE_GAS.get(name, 0)
                if name == "STOP":
                    raise _Halt(b"")
                elif name == "ADD":
                    push(pop() + pop())
                elif name == "MUL":
                    push(pop() * pop())
                elif name == "SUB":
                    a, b = pop(), pop()
                    push(a - b)
                elif name == "DIV":
                    a, b = pop(), pop()
                    push(a // b if b else 0)
                elif name == "SDIV":
                    a, b = _signed(pop()), _signed(pop())
                    push(0 if b == 0 else abs(a) // abs(b)
                         * (1 if (a < 0) == (b < 0) else -1))
                elif name == "MOD":
                    a, b = pop(), pop()
                    push(a % b if b else 0)
                elif name == "SMOD":
                    a, b = _signed(pop()), _signed(pop())
                    push(0 if b == 0 else (abs(a) % abs(b))
                         * (1 if a >= 0 else -1))
                elif name == "ADDMOD":
                    a, b, m = pop(), pop(), pop()
                    push((a + b) % m if m else 0)
                elif name == "MULMOD":
                    a, b, m = pop(), pop(), pop()
                    push((a * b) % m if m else 0)
                elif name == "EXP":
                    a, e = pop(), pop()
                    gas += 50 * ((e.bit_length() + 7) // 8)  # EIP-160
                    push(pow(a, e, 1 << 256))
                elif name == "SIGNEXTEND":
                    k, v = pop(), pop()
                    if k < 31:
                        bit = 8 * (k + 1) - 1
                        if v & (1 << bit):
                            v |= WORD ^ ((1 << (bit + 1)) - 1)
                        else:
                            v &= (1 << (bit + 1)) - 1
                    push(v)
                elif name == "LT":
                    a, b = pop(), pop()
                    push(int(a < b))
                elif name == "GT":
                    a, b = pop(), pop()
                    push(int(a > b))
                elif name == "SLT":
                    a, b = _signed(pop()), _signed(pop())
                    push(int(a < b))
                elif name == "SGT":
                    a, b = _signed(pop()), _signed(pop())
                    push(int(a > b))
                elif name == "EQ":
                    push(int(pop() == pop()))
                elif name == "ISZERO":
                    push(int(pop() == 0))
                elif name == "AND":
                    push(pop() & pop())
                elif name == "OR":
                    push(pop() | pop())
                elif name == "XOR":
                    push(pop() ^ pop())
                elif name == "NOT":
                    push(~pop())
                elif name == "BYTE":
                    i, v = pop(), pop()
                    push((v >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
                elif name == "SHL":
                    s, v = pop(), pop()
                    push(v << s if s < 256 else 0)
                elif name == "SHR":
                    s, v = pop(), pop()
                    push(v >> s if s < 256 else 0)
                elif name == "SAR":
                    s, v = pop(), _signed(pop())
                    push((v >> s if s < 256 else (0 if v >= 0 else -1)))
                elif name == "KECCAK256":
                    offset, size = pop(), pop()
                    data = mread(offset, size)
                    gas += _KECCAK_WORD * ((size + 31) // 32)
                    push(int.from_bytes(keccak256(data), "big"))
                elif name == "ADDRESS":
                    push(addr_int)
                elif name == "ORIGIN" or name == "CALLER":
                    push(caller_int)
                elif name == "CALLVALUE":
                    push(value)
                elif name == "CALLDATALOAD":
                    i = pop()
                    push(int.from_bytes(
                        calldata[i:i + 32].ljust(32, b"\x00"), "big"))
                elif name == "CALLDATASIZE":
                    push(len(calldata))
                elif name == "CALLDATACOPY":
                    dst, src, size = pop(), pop(), pop()
                    gas += _COPY_WORD * ((size + 31) // 32)
                    mwrite(dst, calldata[src:src + size]
                           .ljust(size, b"\x00"))
                elif name == "CODESIZE":
                    push(len(code))
                elif name == "CODECOPY":
                    dst, src, size = pop(), pop(), pop()
                    gas += _COPY_WORD * ((size + 31) // 32)
                    mwrite(dst, code[src:src + size].ljust(size, b"\x00"))
                elif name == "RETURNDATASIZE":
                    push(len(returndata))
                elif name == "RETURNDATACOPY":
                    dst, src, size = pop(), pop(), pop()
                    if src + size > len(returndata):
                        fault("returndata out of bounds")
                    gas += _COPY_WORD * ((size + 31) // 32)
                    mwrite(dst, returndata[src:src + size])
                elif name in ("GASPRICE", "COINBASE", "TIMESTAMP",
                              "NUMBER", "PREVRANDAO", "GASLIMIT",
                              "BASEFEE", "SELFBALANCE"):
                    push(0)  # devnet: no block context
                elif name == "CHAINID":
                    push(31337)
                elif name == "PUSH0":
                    push(0)
                elif name == "POP":
                    pop()
                elif name == "MLOAD":
                    push(int.from_bytes(mread(pop(), 32), "big"))
                elif name == "MSTORE":
                    offset, v = pop(), pop()
                    mwrite(offset, v.to_bytes(32, "big"))
                elif name == "MSTORE8":
                    offset, v = pop(), pop()
                    mwrite(offset, bytes([v & 0xFF]))
                elif name == "SLOAD":
                    slot = pop()
                    gas += (_WARM_ACCESS if slot in warm_slots
                            else _COLD_SLOAD)
                    warm_slots.add(slot)
                    push(self.storage.get(slot, 0))
                elif name == "SSTORE":
                    slot, v = pop(), pop()
                    if slot not in warm_slots:
                        gas += _COLD_SLOAD
                        warm_slots.add(slot)
                    cur = self.storage.get(slot, 0)
                    if cur == v:
                        gas += _WARM_ACCESS
                    elif cur == 0:
                        gas += _SSTORE_SET
                    else:
                        gas += _SSTORE_RESET  # refunds not modeled
                    if v:
                        self.storage[slot] = v
                    else:
                        self.storage.pop(slot, None)
                elif name == "JUMP":
                    dest = pop()
                    if dest not in jumpdests:
                        fault(f"bad jump dest {dest}")
                    pc = dest
                    continue
                elif name == "JUMPI":
                    dest, cond = pop(), pop()
                    if cond:
                        if dest not in jumpdests:
                            fault(f"bad jump dest {dest}")
                        pc = dest
                        continue
                elif name == "PC":
                    push(pc)
                elif name == "MSIZE":
                    push(mem_words_charged * 32)
                elif name == "GAS":
                    push(10_000_000)  # devnet: no gas-limit starvation
                elif name == "JUMPDEST":
                    pass
                elif name == "RETURN":
                    offset, size = pop(), pop()
                    raise _Halt(mread(offset, size))
                elif name == "REVERT":
                    offset, size = pop(), pop()
                    raise EvmRevert(mread(offset, size))
                elif name == "INVALID":
                    fault("INVALID opcode")
                else:
                    raise EigenError(
                        "contract_error",
                        f"unsupported opcode {name} at pc={pc} — this "
                        "single-contract machine implements no "
                        "CALL/CREATE family")
                pc += 1
            raise _Halt(b"")  # fell off the end of code
        except _Halt as h:
            return h.data, gas, logs
