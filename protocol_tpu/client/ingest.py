"""Batched attestation ingest: TPU-validated signatures at scale.

The scalar ingest path (``Client.et_circuit_setup`` →
``SignedAttestationData.recover_public_key``) performs one Poseidon hash
and one EC scalar multiply per attestation on the host — the reference's
ingest hot spot (SURVEY.md §3.1; ``ecdsa/native.rs:298-331``). This
module replaces that per-attestation loop with two device dispatches:

1. all attestation hashes in one batched Poseidon permutation
   (``ops.poseidon_batch``),
2. all pubkey recoveries in one batched GLV + fixed-base-window ladder
   (``ops.secp_batch``). Validity comes from recovery's own binding
   checks (r/s range, curve lift, non-∞) — recover⇒verify is an
   algebraic identity, so the scalar path's second verification ladder
   is redundant work (the reference keeps it only as a debug assert,
   ``ecdsa/native.rs:322-328``; equivalence is property-tested, and
   ``full_verify=True`` re-enables it for audits).

Batches pad to the next power of two so repeated ingests reuse the
ladder's jit cache instead of retracing per batch size. ``Client``
opts in via ``batched_ingest=True`` (host scalar recovery stays the
default: for a handful of attestations the device compile outweighs
the win). Outputs are host objects (PublicKey, 20-byte addresses)
identical to the scalar path — property-tested in
``tests/test_ingest.py``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..crypto.secp256k1 import AffinePoint, PublicKey
from ..models.eigentrust import HASHER_WIDTH


def _pow2_bucket(k: int) -> int:
    """Shape bucket (min 4): jitted kernels specialize on batch size, so
    padding to a power of two reuses compiles across nearby sizes."""
    size = 4
    while size < k:
        size *= 2
    return size


def _ingest_chunk() -> int:
    """Largest single ladder dispatch the tunnel worker survives —
    measured and monitored, not a magic constant: the boundary is
    bisected by tools/probe_lane_crash.py and pinned by
    tests/test_lane_canary.py; PTPU_INGEST_CHUNK overrides."""
    import os

    return int(os.environ.get("PTPU_INGEST_CHUNK", str(1 << 15)))


def hash_recover_pipeline(row_chunks, sig_chunks, _prep=None, _glv=None):
    """Software-pipelined hash + recovery over pre-chunked inputs,
    yielding ``(msgs, (xs, ys, valid))`` per chunk in order.

    While the device runs chunk i's GLV ladder (the dominant span), the
    host hashes chunk i+1 and builds its limbs — the submit/midstage/
    finalize split in ``ops.secp_batch`` plus the hash_submit/finalize
    split in ``ops.poseidon_batch``. Per-chunk results are bit-identical
    to the serial hash_batch → recover_batch sequence (same kernels,
    same order within a chunk). ``sig_chunks`` entries are
    ``(rs, ss, rec_ids)`` lists; ``row_chunks`` entries are hasher input
    rows. This is the single home of the pipeline loop — the client
    ingest path and tools/bench_ingest.py both drive it."""
    from ..ops import secp_batch as sb
    from ..ops.poseidon_batch import get_poseidon_batch_planes

    row_chunks = list(row_chunks)
    sig_chunks = list(sig_chunks)
    assert len(row_chunks) == len(sig_chunks)
    if not row_chunks:
        return
    pb = get_poseidon_batch_planes(HASHER_WIDTH)
    mid = None
    pending_msgs = None
    hh = pb.hash_submit(row_chunks[0])
    for i in range(len(row_chunks)):
        msgs = pb.hash_finalize(hh)
        rs, ss, recs = sig_chunks[i]
        sub = sb.recover_submit(rs, ss, recs, msgs, _prep=_prep)
        if i + 1 < len(row_chunks):
            hh = pb.hash_submit(row_chunks[i + 1])
        if mid is not None:
            yield pending_msgs, sb.recover_finalize(mid)
        pending_msgs = msgs
        mid = sb.recover_midstage(sub, _glv=_glv)
    yield pending_msgs, sb.recover_finalize(mid)


def _att_rows(attestations: Sequence) -> list:
    """Hasher input rows (``Attestation.hash`` operand order) for a
    batch of SignedAttestationData."""
    rows = []
    for signed in attestations:
        att = signed.attestation.to_scalar()
        rows.append([int(att.about), int(att.domain), int(att.value),
                     int(att.message)])
    return rows


def attestation_hashes_batch(attestations: Sequence) -> list:
    """Poseidon attestation hashes for a batch of
    SignedAttestationData, one device dispatch
    (``Attestation.hash``: Poseidon_5(about, domain, value, message, 0)).
    Padded to the same power-of-two bucket as the recovery ladder so the
    permutation compile is shared across nearby batch sizes."""
    from ..ops.poseidon_batch import get_poseidon_batch_planes

    pb = get_poseidon_batch_planes(HASHER_WIDTH)
    rows = _att_rows(attestations)
    k = len(rows)
    rows += [[0, 0, 0, 0]] * (_pow2_bucket(k) - k)
    return pb.hash_batch(rows)[:k]


def recover_signers_batch(attestations: Sequence,
                          full_verify: bool = False):
    """Batched twin of per-attestation ``recover_public_key``.

    Returns (pub_keys, addresses, valid): recovered ``PublicKey``s,
    their 20-byte addresses, and a bool mask. Lanes failing any stage
    come back invalid instead of raising — batch ingest must not let
    one malformed attestation poison the rest.

    Validity is the binding-check set ``recover_batch`` enforces
    (r, s ∈ [1, n), r lifts onto the curve, result ≠ ∞) — by
    construction the recovered key then satisfies the verify equation
    (R' = z·s⁻¹·G + r·s⁻¹·Q = s⁻¹·(z·G + (s·R − z·G)) = R), so the
    second full verification ladder the scalar path runs is a
    re-derivation, not an independent check. The reference itself
    treats it as a debug-grade sanity assert
    (``ecdsa/native.rs:322-328``); SURVEY.md §7.3 licenses dropping it
    with documentation, and the recover⇒verify equivalence is
    property-tested against the scalar oracle
    (``tests/test_secp_batch.py::TestRecoverImpliesVerify``).
    ``full_verify=True`` re-enables the redundant ladder for audits —
    it must never change the mask (also asserted by that suite).
    """
    from ..ops.secp_batch import recover_batch, verify_batch

    if not attestations:
        return [], [], np.zeros(0, dtype=bool)

    k = len(attestations)
    cap = _ingest_chunk()
    if k > cap:
        # beyond one ladder dispatch's measured lane ceiling: chunk AND
        # software-pipeline (hash_recover_pipeline) — host prep of chunk
        # i+1 hides under the device ladder of chunk i
        from ..utils import trace

        rows = _att_rows(attestations)
        sigs = [s.signature.to_signature() for s in attestations]
        row_chunks, sig_chunks, spans = [], [], []
        for lo in range(0, k, cap):
            hi = min(lo + cap, k)
            pad_c = _pow2_bucket(hi - lo) - (hi - lo)
            row_chunks.append(rows[lo:hi] + [[0, 0, 0, 0]] * pad_c)
            sig_chunks.append((
                [s.r for s in sigs[lo:hi]] + [1] * pad_c,
                [s.s for s in sigs[lo:hi]] + [1] * pad_c,
                [s.rec_id for s in sigs[lo:hi]] + [0] * pad_c))
            spans.append(hi - lo)
        xs, ys, valid_parts = [], [], []
        with trace.span("ingest.pipeline", n=k, chunks=len(spans)):
            for (msgs_c, (cx, cy, cvalid)), c, (crs, css, _) in zip(
                    hash_recover_pipeline(row_chunks, sig_chunks),
                    spans, sig_chunks):
                if full_verify:
                    # audit mode: the synchronous verify ladder between
                    # chunks SERIALIZES the pipeline — audited ingest
                    # trades throughput for the redundant check
                    with trace.span("ingest.verify_batch", n=c):
                        ok = verify_batch(crs, css, msgs_c,
                                          list(zip(cx, cy)))
                    cvalid = cvalid & ok
                xs.extend(cx[:c])
                ys.extend(cy[:c])
                valid_parts.append(cvalid[:c])
        valid = np.concatenate(valid_parts)
    else:
        # the Strauss ladder jit-caches per batch shape; bucketing sizes
        # avoids a fresh multi-minute trace per distinct attestation
        # count
        pad = _pow2_bucket(k) - k

        from ..utils import trace

        with trace.span("ingest.hash_batch", n=k):
            msgs = [int(h) for h in attestation_hashes_batch(attestations)]
        sigs = [s.signature.to_signature() for s in attestations]
        rs = [s.r for s in sigs] + [1] * pad
        ss = [s.s for s in sigs] + [1] * pad
        rec = [s.rec_id for s in sigs] + [0] * pad
        msgs_p = msgs + [1] * pad
        with trace.span("ingest.recover_batch", n=k):
            xs, ys, valid = recover_batch(rs, ss, rec, msgs_p)
        if full_verify:
            with trace.span("ingest.verify_batch", n=k):
                ok = verify_batch(rs, ss, msgs_p, list(zip(xs, ys)))
            valid = valid & ok
        xs, ys, valid = xs[:k], ys[:k], valid[:k]

    pub_keys = []
    addresses = []
    for x, y, v in zip(xs, ys, valid):
        if v:
            pk = PublicKey(AffinePoint(int(x), int(y)))
            pub_keys.append(pk)
            addresses.append(pk.to_address_bytes())
        else:
            pub_keys.append(None)
            addresses.append(None)
    return pub_keys, addresses, np.asarray(valid)
