"""Attestation codecs: raw bytes ⇄ eth types ⇄ field scalars.

Wire-format contracts preserved from the reference
(``eigentrust/src/attestation.rs``):

- raw record: about(20) ‖ domain(20) ‖ value(1) ‖ message(32) = 73 bytes
- signature: r(32,be) ‖ s(32,be) ‖ rec_id(1) = 65 bytes
- on-chain payload: signature(65) ‖ value(1) ‖ [message(32) if nonzero]
  = 66 or 98 bytes (attestation.rs to_payload / from_log)
- storage key: b"eigen_trust_" ‖ domain(20) (DOMAIN_PREFIX, build_att_key)
- scalar embedding (to_attestation_fr): about/domain bytes reversed into
  little-endian Fr; value as small int; message via 64-byte LE uniform
  reduction.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto.secp256k1 import Signature, recover_public_key, PublicKey
from ..models.eigentrust import Attestation, SignedAttestation
from ..utils.errors import EigenError
from ..utils.fields import Fr

DOMAIN_PREFIX = b"eigen_trust_"
DOMAIN_PREFIX_LEN = 12


def _require(cond: bool, kind: str, msg: str):
    if not cond:
        raise EigenError(kind, msg)


@dataclass(frozen=True)
class AttestationData:
    """Eth-level attestation: 20-byte about/domain, u8 value, 32-byte msg."""

    about: bytes = b"\x00" * 20
    domain: bytes = b"\x00" * 20
    value: int = 0
    message: bytes = b"\x00" * 32

    def __post_init__(self):
        _require(len(self.about) == 20, "conversion_error", "about must be 20 bytes")
        _require(len(self.domain) == 20, "conversion_error", "domain must be 20 bytes")
        _require(0 <= self.value < 256, "conversion_error", "value must be u8")
        _require(len(self.message) == 32, "conversion_error", "message must be 32 bytes")

    # --- raw 73-byte record (attestation.rs:316-346) ----------------------
    def to_bytes(self) -> bytes:
        return self.about + self.domain + bytes([self.value]) + self.message

    @classmethod
    def from_bytes(cls, data: bytes) -> "AttestationData":
        _require(len(data) == 73, "conversion_error",
                 "raw attestation must be 73 bytes")
        return cls(data[:20], data[20:40], data[40], data[41:])

    # --- storage key (attestation.rs build_att_key) -----------------------
    def get_key(self) -> bytes:
        return DOMAIN_PREFIX + self.domain

    # --- scalar embedding (attestation.rs to_attestation_fr) --------------
    def to_scalar(self) -> Attestation:
        about = Fr(int.from_bytes(self.about, "big"))
        domain = Fr(int.from_bytes(self.domain, "big"))
        value = Fr(self.value)
        # message: 32 LE bytes zero-extended to 64 and uniform-reduced
        message = Fr.from_uniform_bytes_le(self.message[::-1] + b"\x00" * 32)
        return Attestation(about, domain, value, message)


@dataclass(frozen=True)
class SignatureData:
    """Eth-level ECDSA signature triple."""

    r: bytes = b"\x00" * 32
    s: bytes = b"\x00" * 32
    rec_id: int = 0

    def to_bytes(self) -> bytes:
        """65-byte r ‖ s ‖ rec_id (attestation.rs SignatureRaw)."""
        return self.r + self.s + bytes([self.rec_id])

    @classmethod
    def from_bytes(cls, data: bytes) -> "SignatureData":
        _require(len(data) == 65, "conversion_error", "signature must be 65 bytes")
        return cls(data[:32], data[32:64], data[64])

    @classmethod
    def from_signature(cls, sig: Signature) -> "SignatureData":
        return cls(sig.r.to_bytes(32, "big"), sig.s.to_bytes(32, "big"), sig.rec_id)

    def to_signature(self) -> Signature:
        return Signature(
            int.from_bytes(self.r, "big"), int.from_bytes(self.s, "big"), self.rec_id
        )


@dataclass(frozen=True)
class SignedAttestationData:
    """Attestation + signature with the on-chain payload codec."""

    attestation: AttestationData = field(default_factory=AttestationData)
    signature: SignatureData = field(default_factory=SignatureData)

    def to_payload(self) -> bytes:
        """signature(65) ‖ value(1) ‖ [message(32) if nonzero]."""
        out = self.signature.to_bytes() + bytes([self.attestation.value])
        if self.attestation.message != b"\x00" * 32:
            out += self.attestation.message
        return out

    @classmethod
    def from_log(cls, about: bytes, key: bytes, val: bytes) -> "SignedAttestationData":
        """Decode an AttestationCreated log (attestation.rs from_log)."""
        _require(len(val) in (66, 98), "conversion_error",
                 "payload must be 66 or 98 bytes")
        _require(key[:DOMAIN_PREFIX_LEN] == DOMAIN_PREFIX, "parsing_error",
                 "attestation key missing domain prefix")
        signature = SignatureData.from_bytes(val[:65])
        value = val[65]
        message = val[66:] if len(val) == 98 else b"\x00" * 32
        attestation = AttestationData(
            about=about, domain=key[DOMAIN_PREFIX_LEN:], value=value, message=message
        )
        return cls(attestation, signature)

    def recover_public_key(self) -> PublicKey:
        """Recover the attester key from the signature over the Poseidon
        attestation hash (attestation.rs recover_public_key)."""
        att_scalar = self.attestation.to_scalar()
        msg_hash = int(att_scalar.hash())
        return recover_public_key(self.signature.to_signature(), msg_hash)

    def to_signed_scalar(self) -> SignedAttestation:
        return SignedAttestation(
            self.attestation.to_scalar(), self.signature.to_signature()
        )

