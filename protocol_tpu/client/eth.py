"""Ethereum utilities: BIP-39/32 key derivation, address/scalar
conversions, RLP, and legacy transaction signing.

Mirrors ``eigentrust/src/eth.rs``: the 44'/60'/0'/0/i derivation path
(ecdsa_keypairs_from_mnemonic), ``address_from_ecdsa_key`` and
``scalar_from_address``. The reference leans on ethers-rs for BIP-32 and
transaction plumbing; here the primitives are implemented directly on the
standard library (PBKDF2/HMAC-SHA512) and our secp256k1 oracle.
"""

from __future__ import annotations

import hashlib
import hmac

from ..crypto.secp256k1 import EcdsaKeypair, PublicKey, SECP256K1_GENERATOR, N
from ..utils.errors import EigenError
from ..utils.fields import Fr
from ..utils.keccak import keccak256

_HARDENED = 0x8000_0000


def mnemonic_to_seed(mnemonic: str, passphrase: str = "") -> bytes:
    """BIP-39 seed: PBKDF2-HMAC-SHA512 over the NFKD phrase, 2048 rounds."""
    import unicodedata

    phrase = unicodedata.normalize("NFKD", mnemonic.strip())
    salt = unicodedata.normalize("NFKD", "mnemonic" + passphrase)
    return hashlib.pbkdf2_hmac("sha512", phrase.encode(), salt.encode(), 2048)


def _ckd_priv(k: int, chain_code: bytes, index: int) -> tuple:
    """BIP-32 child key derivation (private parent → private child)."""
    if index >= _HARDENED:
        data = b"\x00" + k.to_bytes(32, "big") + index.to_bytes(4, "big")
    else:
        point = SECP256K1_GENERATOR.mul(k)
        prefix = bytes([2 + (point.y & 1)])
        data = prefix + point.x.to_bytes(32, "big") + index.to_bytes(4, "big")
    digest = hmac.new(chain_code, data, hashlib.sha512).digest()
    child = (int.from_bytes(digest[:32], "big") + k) % N
    if child == 0:
        raise EigenError("keys_error", "degenerate child key")
    return child, digest[32:]


def derive_private_key(seed: bytes, path: list) -> int:
    """Derive along a BIP-32 path (ints, hardened = i + 0x80000000)."""
    digest = hmac.new(b"Bitcoin seed", seed, hashlib.sha512).digest()
    k, chain_code = int.from_bytes(digest[:32], "big"), digest[32:]
    for index in path:
        k, chain_code = _ckd_priv(k, chain_code, index)
    return k


def ecdsa_keypairs_from_mnemonic(mnemonic: str, count: int) -> list:
    """Keypairs along 44'/60'/0'/0/i (eth.rs:28-67)."""
    seed = mnemonic_to_seed(mnemonic)
    keys = []
    for i in range(count):
        path = [44 + _HARDENED, 60 + _HARDENED, _HARDENED, 0, i]
        keys.append(EcdsaKeypair(derive_private_key(seed, path)))
    return keys


def address_from_public_key(pub_key: PublicKey) -> bytes:
    """20-byte Ethereum address (eth.rs address_from_ecdsa_key)."""
    return pub_key.to_address_bytes()


def scalar_from_address(address: bytes) -> Fr:
    """Address bytes → Fr via the LE embedding (eth.rs:77-95)."""
    if len(address) != 20:
        raise EigenError("conversion_error", "address must be 20 bytes")
    return Fr.from_bytes_le(address[::-1] + b"\x00" * 12)


# --- RLP + legacy (EIP-155) transaction signing --------------------------


def rlp_encode(item) -> bytes:
    """Minimal RLP: bytes, ints (big-endian minimal), and lists."""
    if isinstance(item, int):
        item = b"" if item == 0 else item.to_bytes((item.bit_length() + 7) // 8, "big")
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _rlp_len(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        payload = b"".join(rlp_encode(x) for x in item)
        return _rlp_len(len(payload), 0xC0) + payload
    raise EigenError("conversion_error", f"cannot RLP-encode {type(item)}")


def _rlp_len(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    len_bytes = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(len_bytes)]) + len_bytes


def sign_legacy_tx(
    keypair: EcdsaKeypair,
    nonce: int,
    gas_price: int,
    gas: int,
    to: bytes,
    value: int,
    data: bytes,
    chain_id: int,
) -> bytes:
    """EIP-155 signed legacy transaction, RLP-encoded raw bytes."""
    sighash = keccak256(
        rlp_encode([nonce, gas_price, gas, to, value, data, chain_id, 0, 0])
    )
    sig = keypair.sign(int.from_bytes(sighash, "big"))
    v = 35 + chain_id * 2 + sig.rec_id
    return rlp_encode([nonce, gas_price, gas, to, value, data, v, sig.r, sig.s])
