"""The Client facade — the SDK's main entry point.

Mirrors the reference ``Client`` (``eigentrust/src/lib.rs:110-674``):
signer setup from a mnemonic, attest, fetch/decode logs, circuit setup
(participant ordering, pubkey recovery, attestation matrix, native
convergence, opinion sponge hash), score calculation, threshold
verification, and proof-generation hooks into the zk layer.

Differences by design:
- the chain is injected (LocalChain simulation or RpcChain), not hardwired
  to an HTTP provider;
- the set size / iteration count are runtime config, not const generics;
- the scale path (`calculate_scores_sparse`) hands raw edge arrays to the
  TPU ConvergeBackend instead of building Python object matrices.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ..models.eigentrust import EigenTrustSet
from ..utils import trace
from ..models.threshold import Threshold
from ..crypto.poseidon import PoseidonSponge
from ..utils.errors import EigenError
from ..utils.fields import Fr
from .attestation import AttestationData, SignatureData, SignedAttestationData
from .chain import AttestationStation, LocalChain
from .circuit_io import ETPublicInputs, ETSetup, Score, ThPublicInputs, ThSetup
from .eth import address_from_public_key, ecdsa_keypairs_from_mnemonic

# Reference instantiation constants (eigentrust-zk/src/circuits/mod.rs:38-59)
DEFAULT_NUM_NEIGHBOURS = 4
DEFAULT_NUM_ITERATIONS = 20
DEFAULT_INITIAL_SCORE = 1000
MIN_PEER_COUNT = 2
DEFAULT_NUM_DECIMAL_LIMBS = 2
DEFAULT_POWER_OF_TEN = 72


def _device_present() -> bool:
    """True when an accelerator backend is live (batched-ingest auto
    mode). Fails closed on jax-less hosts — the scalar path needs no
    device at all."""
    try:
        import jax

        return jax.devices()[0].platform in ("tpu", "axon", "gpu")
    except Exception:
        return False


@dataclass
class ClientConfig:
    """CliConfig twin (eigentrust-cli/src/cli.rs:27-43)."""

    as_address: str = "0x" + "00" * 20
    band_id: str = ""
    band_th: str = "500"
    band_url: str = ""
    chain_id: int = 31337
    domain: str = "0x" + "00" * 20
    node_url: str = "memory"

    @classmethod
    def from_dict(cls, d: dict) -> "ClientConfig":
        known = {f: d[f] for f in cls.__dataclass_fields__ if f in d}
        cfg = cls(**known)
        cfg.chain_id = int(cfg.chain_id)
        return cfg

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__dataclass_fields__}


class Client:
    """SDK facade over chain + trust model + zk layer."""

    def __init__(
        self,
        config: ClientConfig,
        mnemonic: str,
        chain: Optional[AttestationStation] = None,
        num_neighbours: int = DEFAULT_NUM_NEIGHBOURS,
        num_iterations: int = DEFAULT_NUM_ITERATIONS,
        initial_score: int = DEFAULT_INITIAL_SCORE,
        batched_ingest: bool | None = None,
    ):
        self.config = config
        self.mnemonic = mnemonic
        self.keypairs = ecdsa_keypairs_from_mnemonic(mnemonic, 1)
        self.num_neighbours = num_neighbours
        self.num_iterations = num_iterations
        self.initial_score = initial_score
        # True routes signer recovery through the TPU batch path
        # (client.ingest) — worth it for large ingest batches; the host
        # scalar loop stays default for small sets
        # None = auto: batch on an accelerator (the per-attestation
        # scalar path is the reference's ingest hot spot,
        # ecdsa/native.rs:298-331 — on a TPU the batched Poseidon +
        # Strauss kernels win from a few dozen attestations up; on a
        # jax-less or CPU-only host the scalar path stays default)
        self.batched_ingest = batched_ingest
        if chain is not None:
            self.chain = chain
        elif config.node_url == "memory":
            self.chain = LocalChain()
        else:
            from .chain import RpcChain

            self.chain = RpcChain(
                config.node_url,
                bytes.fromhex(config.as_address.removeprefix("0x")),
                int(config.chain_id),
            )

    # --- helpers ----------------------------------------------------------
    @property
    def signer(self):
        return self.keypairs[0]

    def get_scalar_domain(self) -> Fr:
        raw = self._domain_bytes()
        return Fr.from_bytes_le(raw[::-1] + b"\x00" * 12)

    def _domain_bytes(self) -> bytes:
        try:
            raw = bytes.fromhex(self.config.domain.removeprefix("0x"))
        except ValueError as e:
            raise EigenError("config_error", "domain is not valid hex") from e
        if len(raw) != 20:
            raise EigenError("config_error", "domain must be 20 bytes of hex")
        return raw

    # --- write path (lib.rs attest :152-198) ------------------------------
    def attest(self, about: bytes, value: int, message: bytes = b"\x00" * 32) -> str:
        """Sign an attestation about `about` and submit it on-chain."""
        att = AttestationData(
            about=about, domain=self._domain_bytes(), value=value, message=message
        )
        att_fr = att.to_scalar()
        sig = self.signer.sign(int(att_fr.hash()))
        signed = SignedAttestationData(att, SignatureData.from_signature(sig))

        # sanity: recover must give back our own address (lib.rs:176-178)
        recovered = signed.recover_public_key()
        attestor = address_from_public_key(recovered)
        own = address_from_public_key(self.signer.public_key)
        if attestor != own:
            raise EigenError("attestation_error", "self-recovery mismatch")

        about_addr = att.about
        key = att.get_key()
        payload = signed.to_payload()
        if hasattr(self.chain, "attest_signed"):
            return self.chain.attest_signed(self.signer, [(about_addr, key, payload)])
        return self.chain.attest(attestor, [(about_addr, key, payload)])

    # --- read path (lib.rs get_logs/get_attestations :607-645) ------------
    def get_attestations(self, from_block: int = 0) -> list:
        """Fetch and decode this domain's attestations only — the reference
        filters logs by topic3 == build_att_key(domain) (lib.rs:633-645);
        foreign-domain attestations must never reach the opinion layer."""
        from .attestation import DOMAIN_PREFIX

        expected_key = DOMAIN_PREFIX + self._domain_bytes()
        logs = self.chain.get_logs(from_block)
        return [
            SignedAttestationData.from_log(log.about, log.key, log.val)
            for log in logs
            if log.key == expected_key
        ]

    # --- circuit setup (lib.rs et_circuit_setup :339-466) -----------------
    def et_circuit_setup(self, attestations: Sequence[SignedAttestationData]) -> ETSetup:
        n = self.num_neighbours

        # Defense in depth: scoring must only ever see this client's domain
        # regardless of where the attestation list came from (fetch filters
        # too, but CSV files / direct callers bypass that layer).
        domain_bytes = self._domain_bytes()
        attestations = [
            s for s in attestations if s.attestation.domain == domain_bytes
        ]

        # participant set: BTreeSet ordering = sorted unique addresses.
        # Recover each pubkey exactly once (EC scalar mults dominate setup).
        pub_key_map: dict = {}
        origins: list = []
        participants: set = set()
        use_batched = self.batched_ingest
        if use_batched is None:
            use_batched = len(attestations) >= 32 and _device_present()
        if use_batched and attestations:
            from .ingest import recover_signers_batch

            pks, addr_list, valid = recover_signers_batch(attestations)
            if not valid.all():
                bad = int((~valid).argmax())
                raise EigenError("validation_error",
                                 f"attestation {bad} failed batched recovery")
            recovered = list(zip(pks, addr_list))
        else:
            with trace.span("ingest.recover_scalar", n=len(attestations)):
                recovered = [
                    (pk := signed.recover_public_key(),
                     address_from_public_key(pk))
                    for signed in attestations
                ]
        for signed, (pk, origin) in zip(attestations, recovered):
            origins.append(origin)
            pub_key_map[origin] = pk
            participants.add(origin)
            participants.add(signed.attestation.about)
        address_set = sorted(participants)

        if len(address_set) > n:
            raise EigenError(
                "validation_error",
                f"{len(address_set)} participants exceed the set capacity {n}",
            )
        if len(address_set) < MIN_PEER_COUNT:
            raise EigenError(
                "validation_error",
                f"at least {MIN_PEER_COUNT} participants required",
            )

        from .eth import scalar_from_address

        scalar_set = [scalar_from_address(a) for a in address_set]
        scalar_set += [Fr.zero()] * (n - len(scalar_set))
        pub_keys = [
            pub_key_map.get(address_set[i]) if i < len(address_set) else None
            for i in range(n)
        ]

        # attestation matrix in participant order
        matrix: list = [[None] * n for _ in range(n)]
        for signed, origin in zip(attestations, origins):
            i = address_set.index(origin)
            j = address_set.index(signed.attestation.about)
            matrix[i][j] = signed.to_signed_scalar()

        # native set: add members, submit opinions, converge both ways
        domain = self.get_scalar_domain()
        et = EigenTrustSet(n, self.num_iterations, self.initial_score, domain)
        for s in scalar_set[: len(address_set)]:
            et.add_member(s)

        op_hashes = []
        for i, addr in enumerate(address_set):
            pk = pub_key_map.get(addr)
            if pk is not None:
                op_hashes.append(et.update_op(pk, matrix[i]))

        opinion = et.opinion_matrix()
        with trace.span("converge.rational", n=len(address_set)):
            rational_scores = et.converge_rational()
        with trace.span("converge.field", n=len(address_set)):
            field_scores = et.converge()

        sponge = PoseidonSponge()
        sponge.update(op_hashes)
        opinions_hash = sponge.squeeze()

        pub_inputs = ETPublicInputs(scalar_set, field_scores, domain, opinions_hash)
        return ETSetup(
            address_set, matrix, pub_keys, pub_inputs, rational_scores, opinion
        )

    # --- scores (lib.rs calculate_scores :201-236) ------------------------
    def calculate_scores(self, attestations: Sequence[SignedAttestationData]) -> list:
        return self.scores_from_setup(self.et_circuit_setup(attestations))

    def scores_from_setup(self, setup: ETSetup) -> list:
        scores = []
        for addr, score_fr, ratio in zip(
            setup.address_set, setup.pub_inputs.scores, setup.rational_scores
        ):
            scores.append(
                Score(
                    address=addr,
                    score_fr=score_fr.to_bytes_be(),
                    numerator=ratio.numerator,
                    denominator=ratio.denominator,
                )
            )
        return scores

    def calculate_scores_sparse(
        self, n, src, dst, val, valid=None, backend=None, tol=None, alpha=0.0
    ):
        """Scale path: converge raw edge arrays through a ConvergeBackend
        (the seam BASELINE.json's north star mandates)."""
        if backend is None:
            from ..backend import JaxSparseBackend

            backend = JaxSparseBackend()
        import numpy as np

        if valid is None:
            valid = np.ones(n, dtype=bool)
        return backend.converge_edges(
            n, src, dst, val, valid, self.initial_score, self.num_iterations,
            tol=tol, alpha=alpha,
        )

    # --- threshold (lib.rs th_circuit_setup :469-534, verify_threshold) ---
    def th_circuit_setup(
        self,
        attestations: Sequence[SignedAttestationData],
        participant: bytes,
        threshold: int,
        num_limbs: int = DEFAULT_NUM_DECIMAL_LIMBS,
        power_of_ten: int = DEFAULT_POWER_OF_TEN,
    ) -> ThSetup:
        setup = self.et_circuit_setup(attestations)
        try:
            index = setup.address_set.index(participant)
        except ValueError as e:
            raise EigenError(
                "validation_error", "participant not in the attestation set"
            ) from e

        score_fr = setup.pub_inputs.scores[index]
        ratio = setup.rational_scores[index]
        th = Threshold(
            score_fr,
            ratio,
            Fr(threshold),
            num_limbs=num_limbs,
            power_of_ten=power_of_ten,
            num_neighbours=self.num_neighbours,
            initial_score=self.initial_score,
        )
        check = th.check_threshold()

        from .eth import scalar_from_address

        pub_inputs = ThPublicInputs(
            address=scalar_from_address(participant),
            threshold=Fr(threshold),
            threshold_check=check,
        )
        return ThSetup(pub_inputs, th.num_decomposed, th.den_decomposed,
                       et_setup=setup, ratio=ratio)

    def verify_threshold(
        self, attestations, participant: bytes, threshold: int
    ) -> bool:
        return self.th_circuit_setup(
            attestations, participant, threshold
        ).pub_inputs.threshold_check
