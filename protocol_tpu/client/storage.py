"""File persistence: CSV / JSON / binary storage with typed records.

Mirrors the reference's ``Storage`` trait and implementations
(``eigentrust/src/storage.rs``): CSVFileStorage (serde records),
JSONFileStorage, BinFileStorage, plus the two record types with identical
column names and hex-string conventions so CSV files round-trip between
the two frameworks.
"""

from __future__ import annotations

import csv
import json
from abc import ABC, abstractmethod
from dataclasses import asdict, dataclass, fields
from pathlib import Path

from ..utils.errors import EigenError


class Storage(ABC):
    """load/save pair (storage.rs:25-33)."""

    @abstractmethod
    def load(self):
        ...

    @abstractmethod
    def save(self, data) -> None:
        ...


class CSVFileStorage(Storage):
    """CSV persistence of a list of dataclass records."""

    def __init__(self, path, record_type):
        self.path = Path(path)
        self.record_type = record_type

    def load(self) -> list:
        try:
            with open(self.path, newline="") as f:
                reader = csv.DictReader(f)
                names = {f.name for f in fields(self.record_type)}
                out = []
                for row in reader:
                    extra = set(row) - names
                    missing = names - set(row)
                    if extra or missing:
                        raise EigenError(
                            "parsing_error",
                            f"CSV columns mismatch: extra={sorted(extra)}"
                            f" missing={sorted(missing)}",
                        )
                    if any(v is None for v in row.values()):
                        raise EigenError("parsing_error", "short CSV row")
                    out.append(self.record_type(**row))
                return out
        except OSError as e:
            raise EigenError("file_io_error", str(e)) from e

    def save(self, data: list) -> None:
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "w", newline="") as f:
                writer = csv.DictWriter(
                    f, fieldnames=[fld.name for fld in fields(self.record_type)]
                )
                writer.writeheader()
                for record in data:
                    writer.writerow(asdict(record))
        except OSError as e:
            raise EigenError("file_io_error", str(e)) from e


class JSONFileStorage(Storage):
    """JSON persistence of any json-serializable value (storage.rs:112-144)."""

    def __init__(self, path):
        self.path = Path(path)

    def load(self):
        try:
            with open(self.path) as f:
                return json.load(f)
        except OSError as e:
            raise EigenError("file_io_error", str(e)) from e
        except json.JSONDecodeError as e:
            raise EigenError("parsing_error", str(e)) from e

    def save(self, data) -> None:
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "w") as f:
                json.dump(data, f, indent=2)
        except OSError as e:
            raise EigenError("file_io_error", str(e)) from e


class BinFileStorage(Storage):
    """Raw bytes persistence (storage.rs:148-180) — kzg params, keys,
    proofs."""

    def __init__(self, path):
        self.path = Path(path)

    def load(self) -> bytes:
        try:
            return self.path.read_bytes()
        except OSError as e:
            raise EigenError("file_io_error", str(e)) from e

    def save(self, data: bytes) -> None:
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self.path.write_bytes(bytes(data))
        except OSError as e:
            raise EigenError("file_io_error", str(e)) from e


@dataclass
class ScoreRecord:
    """One scores.csv row (storage.rs:183-243); all values strings with the
    reference's conventions (0x-hex address/score_fr, decimal num/den)."""

    peer_address: str
    score_fr: str
    numerator: str
    denominator: str
    score: str

    @classmethod
    def from_score(cls, score) -> "ScoreRecord":
        """From a circuit_io.Score."""
        return cls(
            peer_address="0x" + score.address.hex(),
            score_fr="0x" + score.score_fr.hex(),
            numerator=str(score.numerator),
            denominator=str(score.denominator),
            score=str(score.score_int),
        )


@dataclass
class AttestationRecord:
    """One attestations.csv row (storage.rs:246-307)."""

    about: str
    domain: str
    value: str
    message: str
    sig_r: str
    sig_s: str
    rec_id: str

    @classmethod
    def from_signed(cls, signed) -> "AttestationRecord":
        """From a client.attestation.SignedAttestationData."""
        return cls(
            about="0x" + signed.attestation.about.hex(),
            domain="0x" + signed.attestation.domain.hex(),
            value=str(signed.attestation.value),
            message="0x" + signed.attestation.message.hex(),
            sig_r="0x" + signed.signature.r.hex(),
            sig_s="0x" + signed.signature.s.hex(),
            rec_id=str(signed.signature.rec_id),
        )

    def to_signed(self):
        from .attestation import AttestationData, SignatureData, SignedAttestationData

        def unhex(s: str, length: int) -> bytes:
            raw = bytes.fromhex(s.removeprefix("0x"))
            if len(raw) != length:
                raise EigenError("parsing_error", f"expected {length} bytes, got {len(raw)}")
            return raw

        try:
            att = AttestationData(
                about=unhex(self.about, 20),
                domain=unhex(self.domain, 20),
                value=int(self.value),
                message=unhex(self.message, 32),
            )
            sig = SignatureData(
                r=unhex(self.sig_r, 32), s=unhex(self.sig_s, 32), rec_id=int(self.rec_id)
            )
        except ValueError as e:
            raise EigenError("parsing_error", str(e)) from e
        return SignedAttestationData(att, sig)
