"""File layout: assets directory, artifact naming, secrets.

Mirrors ``eigentrust-cli/src/fs.rs``: the EigenFile naming scheme
(kzg-params-{k}.bin, {et|th}-proving-key.bin, {et|th}-proof.bin,
{et|th}-public-inputs.bin), assets-dir resolution, and the MNEMONIC env
secret with an insecure development default.
"""

from __future__ import annotations

import os
from pathlib import Path

from ..utils.errors import EigenError

# well-known development mnemonic (same spirit as the reference's insecure
# default, fs.rs:87-93 — never use with real funds)
INSECURE_MNEMONIC = "test test test test test test test test test test test junk"


def assets_dir(override: str | None = None) -> Path:
    """Assets dir: --assets flag > EIGEN_ASSETS env > ./assets."""
    path = Path(override or os.environ.get("EIGEN_ASSETS", "assets"))
    path.mkdir(parents=True, exist_ok=True)
    return path


def load_mnemonic() -> str:
    """MNEMONIC env with insecure default (warns via return contract)."""
    return os.environ.get("MNEMONIC", INSECURE_MNEMONIC)


class EigenFile:
    """Artifact path naming (fs.rs:50-84)."""

    def __init__(self, assets: Path):
        self.assets = assets

    def kzg_params(self, k: int) -> Path:
        return self.assets / f"kzg-params-{k}.bin"

    def et_proving_key(self) -> Path:
        return self.assets / "et-proving-key.bin"

    def th_proving_key(self) -> Path:
        return self.assets / "th-proving-key.bin"

    def et_proof(self) -> Path:
        return self.assets / "et-proof.bin"

    def et_verifier(self) -> Path:
        return self.assets / "et-verifier.yul"

    def et_proof_meta(self) -> Path:
        """Sidecar recording how et-proof.bin was produced (transcript
        kind) so verify verbs can't silently replay the wrong hash."""
        return self.assets / "et-proof.meta.json"

    def et_public_inputs(self) -> Path:
        return self.assets / "et-public-inputs.bin"

    def th_proof(self) -> Path:
        return self.assets / "th-proof.bin"

    def th_public_inputs(self) -> Path:
        return self.assets / "th-public-inputs.bin"

    def attestations_csv(self) -> Path:
        return self.assets / "attestations.csv"

    def scores_csv(self) -> Path:
        return self.assets / "scores.csv"

    def config_json(self) -> Path:
        return self.assets / "config.json"

    def chain_json(self) -> Path:
        return self.assets / "chain.json"

    def service_state_dir(self) -> Path:
        """Root of the serve daemon's durable state store (WAL, graph
        snapshots, operator cache, block cursor) — ``protocol_tpu.store``."""
        return self.assets / "service-state"

    def proofs_dir(self) -> Path:
        """Persisted proof artifacts, one directory per job id with the
        stable file names ``proof.bin`` / ``public-inputs.bin`` /
        ``job.json`` (the service twin of ``et_proof()`` and friends)."""
        return self.assets / "proofs"

    def read(self, path: Path) -> bytes:
        if not path.exists():
            raise EigenError("file_io_error", f"missing artifact: {path}")
        return path.read_bytes()
