"""Command-line front end (reference: the ``eigentrust-cli`` crate)."""

from .main import main, build_parser

__all__ = ["main", "build_parser"]
