"""The ``scenario`` verb: the adversarial harness from the command line.

Three actions over :mod:`protocol_tpu.scenarios`:

- ``list`` — the topology catalog with every tunable knob and default;
- ``run`` — one seeded {topology × semiring} run, JSON report on
  stdout (and ``--out``); byte-identical across runs of the same seed
  unless ``--timing`` opts into wall-clock fields;
- ``report`` — render a saved run JSON as a human-readable summary.

All output is JSON (list/run) so the bench and smoke drivers shell out
to the same code path they'd import.
"""

from __future__ import annotations

import json
import sys

from ..utils.errors import EigenError


def _dump(obj) -> str:
    return json.dumps(obj, sort_keys=True, indent=2)


def handle_scenario(args, files, config):
    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from ..scenarios import list_scenarios, run_scenario

    if args.action == "list":
        print(_dump(list_scenarios()))
        return 0

    if args.action == "report":
        if not args.json:
            raise EigenError("validation_error",
                             "scenario report needs --json PATH")
        # resolve like `run --out`: relative paths live under assets
        # (falling back to the cwd so existing absolute-ish habits keep
        # working) — `run --out r.json` then `report --json r.json`
        # must round-trip
        from pathlib import Path

        path = Path(args.json)
        if not path.is_absolute() and not path.exists():
            path = files.assets / path
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            raise EigenError("file_io_error",
                             f"cannot read report: {e}") from e
        r = report.get("robustness", {})
        disp = r.get("honest_rank_displacement", {})
        top = r.get("attackers_in_top", {})
        print(f"topology {report.get('topology')} "
              f"({report.get('peers')} peers, {report.get('edges')} edges, "
              f"{report.get('attackers')} attackers), "
              f"semiring {report.get('semiring')}, seed "
              f"{report.get('seed')}, engine {report.get('engine')}")
        print(f"  attacker mass capture: "
              f"{r.get('attacker_mass_capture', 0.0):.4f} "
              f"(baseline {r.get('baseline_attacker_mass', 0.0):.4f})")
        print(f"  honest rank displacement: mean {disp.get('mean', 0.0):.2f}, "
              f"max {disp.get('max', 0)}, moved "
              f"{disp.get('moved_fraction', 0.0):.2%}")
        print(f"  attackers in top {top.get('top')}: {top.get('count')}")
        bound = r.get("iteration_bound")
        within = ("n/a (alpha=0: no spectrum-free bound)"
                  if bound is None else
                  f"bound {bound} -> "
                  f"{'WITHIN' if r.get('within_bound') else 'EXCEEDED'}")
        print(f"  iterations: {r.get('iterations')} ({within})")
        return 0

    try:
        report = run_scenario(
            args.topology, peers=args.peers,
            attacker_fraction=args.attacker_fraction,
            semiring=args.semiring, seed=args.seed, alpha=args.alpha,
            tol=args.tol, max_iterations=args.max_iterations,
            engine=args.engine, baseline=not args.no_baseline,
            timing=args.timing)
    except ValueError as e:
        raise EigenError("validation_error", str(e)) from e
    text = _dump(report)
    if args.out:
        from pathlib import Path

        out = Path(args.out)
        if not out.is_absolute():
            out = files.assets / out
        out.write_text(text + "\n")
        print(f"wrote {out}", file=sys.stderr)
    print(text)
    return 0
