"""The ``profile`` CLI verb: run a workload under the instrument layer
and emit a merged per-stage report.

Closes the ROADMAP "device-side (xprof) timeline correlation" remainder:
one command runs a chosen workload — a synthetic prove (host or TPU
path, whichever ``prove_auto`` picks), a synthetic score refresh, or a
capture window on a LIVE serve daemon via its proof job queue — with

- **sync-span mode** on by default (``trace.sync_spans()``), so stage
  spans attribute device work accurately instead of dispatch-skewed;
- an optional **xprof capture** (``--xprof DIR`` →
  ``trace.device_trace``) whose start/stop events share the workload's
  trace id with the JSONL span stream (``--jsonl PATH``) — the offline
  xprof timeline joins the span stream by trace id + wall clock;
- **XLA compile tracking** installed, so the report separates compile
  from execute;

and then prints the per-stage table from the
``ptpu_prover_stage_seconds``/span aggregates: count, total, share of
the prove wall time. ``--min-coverage`` turns the report into an
assertion that the named stages account for at least that fraction of
the total — the "stage times sum to the prove wall time" honesty check
``tools/perf_gate.py`` and the test suite reuse.
"""

from __future__ import annotations

import json
import random
import sys
import time

from ..utils.errors import EigenError


# --- workload runners (shared with tools/perf_gate.py) ---------------------

def synthetic_circuit(gates: int = 64, lookup_bits: int = 6,
                      seed: int = 7, public_input: int = 12345,
                      lookup_row: bool = False):
    """The ONE tiny-circuit generator behind every synthetic proving
    workload — the ``profile`` verb, the perf gate, ``bench.py
    --proofs`` and the serve smoke's pool phase all build circuits
    here, so the shape they measure cannot silently drift apart.
    ``lookup_row`` adds a copy-constrained lookup usage (the prove
    workload wants the lookup argument exercised; throughput workloads
    skip it)."""
    from ..utils.fields import BN254_FR_MODULUS as R
    from ..zk.plonk import ConstraintSystem

    rng = random.Random(seed)
    cs = ConstraintSystem(lookup_bits=lookup_bits)
    for _ in range(gates):
        a, b = rng.randrange(50), rng.randrange(50)
        cs.add_row([a, b, (a * b + a) % R], q_a=1, q_mul_ab=1, q_c=R - 1)
    if lookup_row:
        lk = cs.lookup_row(37)
        row = cs.add_row([37], q_a=1, q_const=R - 37)
        cs.copy(lk, (0, row))
    cs.public_input(public_input)
    cs.check_satisfied()
    return cs


def run_prove_workload(k: int = 7, gates: int = 64, repeat: int = 1,
                       seed: int = 7) -> dict:
    """Keygen + prove a synthetic circuit on a 2^k domain through
    ``prove_auto`` (host path on a jax-less/CPU box, TPU path on an
    accelerator — both are stage-attributed). Returns workload metadata;
    timings land in the process tracer."""
    from .. import native
    from ..zk import prover_fast as pf
    from ..zk.plonk import verify

    if not native.available():
        raise EigenError("config_error",
                         "the prove workload needs the native toolchain")
    cs = synthetic_circuit(gates=gates, seed=seed, lookup_row=True)
    params = pf.setup_params_fast(k, seed=b"profile")
    pk = pf.keygen_fast(params, cs, k=k, eval_pk="auto")
    proof = b""
    for _ in range(max(1, repeat)):
        proof = pf.prove_auto(params, pk, cs)
    if not verify(params, pk, cs.public_values(), proof):
        raise EigenError("verification_error",
                         "profile workload produced an invalid proof")
    return {"workload": "prove", "k": k, "gates": gates,
            "repeat": repeat, "rows": cs.num_rows}


def run_refresh_workload(n: int = 2000, m: int = 4,
                         engine: str = "gather", tol: float = 1e-6,
                         repeat: int = 1, seed: int = 11) -> dict:
    """Adaptive converge of a synthetic Barabási–Albert trust graph
    through the ConvergeBackend seam (the serve daemon's refresh path):
    exercises operator build, the converge sweeps, and the iteration/
    residual gauges."""
    from ..backend import JaxRoutedBackend, JaxSparseBackend
    from ..graph import barabasi_albert_edges

    import numpy as np

    src, dst, val = barabasi_albert_edges(n, m, seed=seed)
    valid = np.ones(n, dtype=bool)
    backend = (JaxRoutedBackend() if engine == "routed"
               else JaxSparseBackend())
    iters = delta = None
    for _ in range(max(1, repeat)):
        _, iters, delta = backend.converge_edges(
            n, src, dst, val, valid, 1000.0, 500, tol=tol)
    return {"workload": "refresh", "n": n, "edges": len(src),
            "engine": engine, "iterations": int(iters),
            "residual": float(delta), "repeat": repeat}


def run_delta_workload(n: int = 4000, m: int = 4, batches: int = 10,
                       batch_edges: int = 200, seed: int = 17) -> dict:
    """The serve daemon's write path at churn: one full routed build
    (``routed.plan_build`` span), a DeltaEngine anchor, then weight-
    revision batches absorbed in place (``delta.classify`` /
    ``delta.revise`` / ``delta.structural`` / ``delta.renorm`` spans)
    and one partial refresh over the dirty frontier. Timings land in
    the process tracer; tools/perf_gate.py gates the delta-apply
    stages against the full-build stage."""
    import numpy as np

    from ..graph import barabasi_albert_edges, filter_edges
    from ..incremental import DeltaEngine, partial_refresh, revision_batch
    from ..ops.routed import build_routed_operator

    rng = np.random.default_rng(seed)
    src, dst, val = barabasi_albert_edges(n, m, seed=seed)
    valid = np.ones(n, dtype=bool)
    fsrc, fdst, _, _, _, raw, _ = filter_edges(n, src, dst, val, valid,
                                               return_raw=True)
    cur = raw.copy()
    op = build_routed_operator(n, src, dst, val, valid)
    eng = DeltaEngine.anchor(n, src, dst, val, valid, op)
    s_pub, iters, delta = eng.converge(
        eng.initial_node_scores(1000.0), 300, 1e-6)
    eng.take_frontier()
    for _ in range(max(1, batches)):
        deltas = revision_batch(rng, fsrc, fdst, cur, batch_edges)
        if not eng.apply_deltas(deltas):
            raise EigenError("internal_error",
                             f"delta batch rejected: {eng.stats}")
    frontier, _ = eng.take_frontier()
    res = partial_refresh(eng, s_pub, frontier, 1e-6, 300,
                          frontier_limit=n)
    return {"workload": "delta", "n": n, "edges": len(fsrc),
            "batches": batches, "batch_edges": batch_edges,
            "tail": len(eng.tail_index),
            "partial_sweeps": None if res is None else res.sweeps}


def run_sublinear_workload(n: int = 3000, m: int = 4,
                           seed: int = 19) -> dict:
    """The sublinear refresh ladder end to end, stage-attributed for
    the perf gate: one routed build + anchor (``routed.plan_build``),
    a LOCALIZED churn window served by the device partial sweep
    (``partial.device`` span — ``device_threshold=0`` forces the
    kernel), a FLOODED churn window pushed past a tight frontier limit
    so the partially-observed mode serves it (``partial.sampled``
    span), and the full-sweep oracle both are checked against
    (``converge.edges``). A ladder regression — a rung silently
    falling through to the full sweep, or the device kernel slowing
    down — moves these stages against the committed baseline."""
    import numpy as np

    from ..graph import barabasi_albert_edges, filter_edges
    from ..incremental import DeltaEngine, ladder_refresh, revision_batch
    from ..ops.routed import build_routed_operator

    rng = np.random.default_rng(seed)
    src, dst, val = barabasi_albert_edges(n, m, seed=seed)
    valid = np.ones(n, dtype=bool)
    fsrc, fdst, _, _, _, raw, _ = filter_edges(n, src, dst, val, valid,
                                               return_raw=True)
    cur = raw.copy()
    op = build_routed_operator(n, src, dst, val, valid)
    # alpha: geometric convergence keeps the workload's sweep counts
    # stable across seeds (the gate times stages, not mixing rates)
    eng = DeltaEngine.anchor(n, src, dst, val, valid, op, alpha=0.15)
    s_pub, iters, delta = eng.converge(
        eng.initial_node_scores(1000.0), 300, 1e-6)
    eng.take_frontier()

    # localized churn -> device partial sweep
    deltas = revision_batch(rng, fsrc, fdst, cur, 20)
    if not eng.apply_deltas(deltas):
        raise EigenError("internal_error",
                         f"delta batch rejected: {eng.stats}")
    frontier, _ = eng.take_frontier()
    res_dev, mode_dev = ladder_refresh(
        eng, s_pub, frontier, 1e-6, 300, n, device_threshold=0,
        sample_budget=n, error_budget=1e-3)
    s_pub = s_pub if res_dev is None else res_dev.scores

    # flooded churn past a tight frontier limit -> sampled mode
    deltas = revision_batch(rng, fsrc, fdst, cur, 400)
    if not eng.apply_deltas(deltas):
        raise EigenError("internal_error",
                         f"delta batch rejected: {eng.stats}")
    frontier, _ = eng.take_frontier()
    res_smp, mode_smp = ladder_refresh(
        eng, s_pub, frontier, 1e-6, 300, max(len(frontier) // 4, 1),
        device_threshold=0, sample_budget=n, error_budget=1e-3)

    # the full-sweep oracle the sublinear modes are measured against
    s_full, it_f, d_f = eng.converge(s_pub, 300, 1e-6)
    return {"workload": "sublinear", "n": n, "edges": len(fsrc),
            "device_mode": mode_dev, "sampled_mode": mode_smp,
            "device_sweeps": None if res_dev is None else res_dev.sweeps,
            "sampled_sweeps": None if res_smp is None else res_smp.sweeps,
            "full_iterations": int(it_f)}


def run_scenario_workload(peers: int = 4000, seed: int = 23) -> dict:
    """One mid-scale adversarial scenario per semiring, stage-attributed
    for the perf gate: a seeded sybil-ring build converged through the
    ConvergeBackend seam under (+,*) and again under (max,min), each
    with its attack-free baseline control — so the gated stages are the
    whole semiring sweep surface (``scenario.run`` wrapping the
    ``converge.edges`` sweeps for both algebras). A regression here —
    the generalized sweep kernel slowing down, the seam forcing a
    recompile per semiring, or the topology builder turning
    superlinear — moves these stages against the committed baseline."""
    from ..scenarios import run_scenario

    # alpha matches the scenario harness default: the damped bound
    # keeps iteration counts seed-stable (the gate times stages, not
    # mixing rates), and both semiring runs share one graph build seed
    reports = {
        name: run_scenario("sybil-ring", peers=peers, seed=seed,
                           semiring=name, alpha=0.1, engine="sparse")
        for name in ("plusmul", "maxplus")
    }
    return {"workload": "scenario", "peers": peers,
            "edges": reports["plusmul"]["edges"],
            "iterations": {name: rep["scores"]["iterations"]
                           for name, rep in reports.items()},
            "capture": {name: rep["robustness"]["attacker_mass_capture"]
                        for name, rep in reports.items()}}


def run_commits_workload(k: int = 13, columns: int = 8,
                         seed: int = 23) -> dict:
    """The commit engine in isolation at a size where the MSM is the
    cost: one batched flush of ``columns`` Lagrange-basis eval columns
    and one of SRS coefficient columns at 2^k, stage-attributed as
    ``commit.bench_evals`` / ``commit.bench_coeffs`` (batched label
    from the engine). One column of each batch is re-committed through
    the serial oracle and compared, so the gate can never lock in a
    fast-but-wrong batch. tools/perf_gate.py's ``commits`` workload
    gates these stages against the committed baseline."""
    import random

    import numpy as np

    from .. import native
    from ..utils.fields import BN254_FR_MODULUS as R
    from ..zk import prover_fast as pf
    from ..zk.commit_engine import CommitEngine

    if not native.available():
        raise EigenError("config_error",
                         "the commits workload needs the native "
                         "toolchain")
    params = pf.setup_params_fast(k, seed=b"commit-bench")
    rng = random.Random(seed)
    n = 1 << k
    blob = np.frombuffer(
        rng.getrandbits(8 * 32 * n * columns).to_bytes(
            32 * n * columns, "little"),
        dtype="<u8").reshape(columns, n, 4).copy()
    blob[:, :, 3] &= (1 << 59) - 1  # keep scalars < R
    eng = CommitEngine(params)
    with pf._stage("commit.bench_evals", k, "host",
                   labels=eng.stage_labels()):
        for i in range(columns):
            eng.submit_evals(f"col{i}", blob[i])
        eval_pts = eng.flush()
    with pf._stage("commit.bench_coeffs", k, "host",
                   labels=eng.stage_labels()):
        for i in range(columns):
            eng.submit_coeffs(f"col{i}", blob[i])
        coeff_pts = eng.flush()
    if eval_pts[0] != pf._msm_signed(pf.lagrange_limbs(params), blob[0]):
        raise EigenError("internal_error",
                         "batched eval commit diverged from the serial "
                         "oracle")
    if coeff_pts[-1] != pf.commit_limbs(params, blob[-1]):
        raise EigenError("internal_error",
                         "batched coeff commit diverged from the "
                         "serial oracle")
    return {"workload": "commits", "k": k, "columns": columns,
            "batched": eng.batching}


def run_proofs_workload(k: int = 7, gates: int = 64, jobs: int = 6,
                        workers: int = 2, seed: int = 7) -> dict:
    """Real host-path proves through a ``workers``-worker ProofWorkerPool
    (the serve daemon's proof path at pool scale): exercises per-worker
    prover isolation, cache-affinity scheduling and the submit→run
    pipeline. Stage timings land in ``ptpu_prover_stage_seconds`` (with
    worker labels) and the ``service.proof`` spans; the perf gate
    tracks both so a scheduling regression (queue stall, lost wakeup,
    serialization across workers) shows up as wall-time growth against
    the committed baseline."""
    from .. import native
    from ..service.faults import FaultInjector
    from ..service.pool import ProofWorkerPool
    from ..zk import prover_fast as pf

    if not native.available():
        raise EigenError("config_error",
                         "the proofs workload needs the native toolchain")
    cs = synthetic_circuit(gates=gates, seed=seed)
    params = pf.setup_params_fast(k, seed=b"profile-pool")
    pk = pf.keygen_fast(params, cs, k=k, eval_pk="auto")
    reference = pf.prove_fast(params, pk, cs, randint=lambda: 424242)

    def prove(p):
        return {"proof": pf.prove_fast(
            params, pk, cs, randint=lambda: 424242).hex()}

    pool = ProofWorkerPool(
        {"eigentrust": prove}, capacity=max(jobs, 8), workers=workers,
        faults=FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0}),
        worker_env=lambda w: pf.worker_isolation(w.name, w.device))
    pool.start()
    submitted = [pool.submit("eigentrust", {}) for _ in range(jobs)]
    deadline = time.monotonic() + 300.0
    while pool.completed + pool.failed < jobs:
        if time.monotonic() > deadline:
            raise EigenError("internal_error", "proof pool stalled")
        time.sleep(0.01)
    for job in submitted:
        got = pool.get(job.job_id)
        if got.status != "done" or \
                bytes.fromhex(got.result["proof"]) != reference:
            raise EigenError(
                "verification_error",
                f"pool proof diverged from the single-worker output "
                f"({got.status}: {got.error})")
    status = pool.pool_status()
    pool.drain(10.0)
    return {"workload": "proofs", "k": k, "gates": gates, "jobs": jobs,
            "workers": workers,
            "per_worker": {w["worker"]: w["jobs_run"]
                           for w in status["workers"]}}


def run_sharded_workload(k: int = 7, gates: int = 64, workers: int = 2,
                         jobs: int = 3, seed: int = 9) -> dict:
    """Real host-path proves SHARDED across a 2-worker pool (worker
    lending, ``pool.shard_kinds``): each prove's commit columns,
    quotient row chunks and opening folds fan out to the idle worker
    and rendezvous in submission order. Byte parity vs the direct
    single-worker prove is asserted per job, and the run must have
    actually sharded (``ptpu_prove_shards_total`` > 0) — a fan-out
    regression that silently serializes would otherwise still pass.
    The perf gate tracks the ``service.proof`` and ``prove.shard``
    spans against the committed baseline."""
    from .. import native
    from ..service.faults import FaultInjector
    from ..service.pool import ProofWorkerPool
    from ..utils import trace
    from ..zk import prover_fast as pf

    if not native.available():
        raise EigenError("config_error",
                         "the sharded workload needs the native "
                         "toolchain")
    cs = synthetic_circuit(gates=gates, seed=seed)
    params = pf.setup_params_fast(k, seed=b"profile-shard")
    pk = pf.keygen_fast(params, cs, k=k, eval_pk="auto")
    reference = pf.prove_fast(params, pk, cs, randint=lambda: 424242)
    shards0 = trace.counter_total("prove_shards")

    def prove(p):
        return {"proof": pf.prove_fast(
            params, pk, cs, randint=lambda: 424242).hex()}

    pool = ProofWorkerPool(
        {"eigentrust": prove}, capacity=max(jobs, 8), workers=workers,
        faults=FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0}),
        shard_kinds={"eigentrust"}, shard_cap=4,
        worker_env=lambda w: pf.worker_isolation(w.name, w.device))
    pool.start()
    submitted = [pool.submit("eigentrust", {}) for _ in range(jobs)]
    deadline = time.monotonic() + 300.0
    while pool.completed + pool.failed < jobs:
        if time.monotonic() > deadline:
            raise EigenError("internal_error", "sharded pool stalled")
        time.sleep(0.01)
    for job in submitted:
        got = pool.get(job.job_id)
        if got.status != "done" or \
                bytes.fromhex(got.result["proof"]) != reference:
            raise EigenError(
                "verification_error",
                f"sharded proof diverged from the direct prove "
                f"({got.status}: {got.error})")
    shards = trace.counter_total("prove_shards") - shards0
    if shards <= 0:
        raise EigenError("internal_error",
                         "sharding never engaged (0 shard units)")
    status = pool.pool_status()
    pool.drain(10.0)
    return {"workload": "sharded", "k": k, "gates": gates,
            "jobs": jobs, "workers": workers, "shards": int(shards),
            "lent": {w["worker"]: w["shards_run"]
                     for w in status["workers"]}}


def run_fabric_workload(k: int = 7, gates: int = 64, jobs: int = 3,
                        seed: int = 9) -> dict:
    """Real host-path proves sharded across the CROSS-PROCESS fabric
    (``zk/fabric.py``): a 1-worker pool publishes portable units to a
    throwaway FabricStore and an external worker loop (in-thread here
    — the gate measures the serialization + rendezvous overhead, not
    process spawn) claims, executes and returns them. Byte parity vs
    the direct prove is asserted per job, and at least one unit must
    have been applied from the fabric (``ptpu_fabric_units_total`` > 0)
    — a publish/claim regression that silently degrades to all-local
    would otherwise still pass. The perf gate tracks ``service.proof``,
    ``prove.shard`` and ``fabric.unit`` spans against the baseline."""
    import shutil
    import tempfile
    import threading

    from .. import native
    from ..service.faults import FaultInjector
    from ..service.pool import ProofWorkerPool
    from ..utils import trace
    from ..zk import prover_fast as pf
    from ..zk.fabric import FabricStore, run_worker

    if not native.available():
        raise EigenError("config_error",
                         "the fabric workload needs the native "
                         "toolchain")
    cs = synthetic_circuit(gates=gates, seed=seed)
    params = pf.setup_params_fast(k, seed=b"profile-shard")
    pk = pf.keygen_fast(params, cs, k=k, eval_pk="auto")
    reference = pf.prove_fast(params, pk, cs, randint=lambda: 424242)
    units0 = trace.counter_total("fabric_units")

    def prove(p):
        return {"proof": pf.prove_fast(
            params, pk, cs, randint=lambda: 424242).hex()}

    root = tempfile.mkdtemp(prefix="ptpu-fabric-")
    fabric = FabricStore(root, lease_ttl=5.0)
    pool = ProofWorkerPool(
        {"eigentrust": prove}, capacity=max(jobs, 8), workers=1,
        faults=FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0}),
        shard_kinds={"eigentrust"}, shard_cap=4,
        worker_env=lambda w: pf.worker_isolation(w.name, w.device),
        fabric=fabric)
    pool.start()
    stop = threading.Event()
    worker = threading.Thread(
        target=run_worker, args=(fabric, "fw-gate"),
        kwargs={"poll": 0.01, "stop": stop},
        name="ptpu-profile-worker", daemon=True)
    worker.start()
    try:
        deadline = time.monotonic() + 60.0
        while fabric.workers_live() < 1:
            fabric._workers_cache = (0.0, 0)
            if time.monotonic() > deadline:
                raise EigenError("read_write_error",
                                 "fabric worker never registered")
            time.sleep(0.01)
        submitted = [pool.submit("eigentrust", {}) for _ in range(jobs)]
        deadline = time.monotonic() + 300.0
        while pool.completed + pool.failed < jobs:
            if time.monotonic() > deadline:
                raise EigenError("resource_error", "fabric pool stalled")
            time.sleep(0.01)
        for job in submitted:
            got = pool.get(job.job_id)
            if got.status != "done" or \
                    bytes.fromhex(got.result["proof"]) != reference:
                raise EigenError(
                    "verification_error",
                    f"fabric proof diverged from the direct prove "
                    f"({got.status}: {got.error})")
        units = trace.counter_total("fabric_units") - units0
        if units <= 0:
            raise EigenError("verification_error",
                             "the fabric never engaged (0 units "
                             "applied from the external worker)")
    finally:
        stop.set()
        worker.join(timeout=10.0)
        pool.drain(10.0)
        shutil.rmtree(root, ignore_errors=True)
    return {"workload": "fabric", "k": k, "gates": gates,
            "jobs": jobs, "units": int(units)}


def run_daemon_capture(url: str, seconds: float) -> dict:
    """Submit a ``profile`` job to a live daemon and wait for the
    capture window to close; returns the job result (xprof log dir on
    the daemon's filesystem)."""
    import urllib.error
    import urllib.request

    def call(method, path, body=None):
        req = urllib.request.Request(
            url.rstrip("/") + path, method=method,
            data=(json.dumps(body).encode() if body is not None
                  else None),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            # 429 = queue backpressure, 503 = draining — structured
            # errors, not tracebacks
            raise EigenError(
                "service_busy",
                f"daemon rejected {method} {path}: HTTP {e.code} "
                f"{e.read()[:200].decode(errors='replace')}") from e
        except urllib.error.URLError as e:
            raise EigenError(
                "connection_error",
                f"cannot reach daemon at {url}: {e.reason}") from e

    job = call("POST", "/proofs",
               {"kind": "profile", "params": {"seconds": seconds}})
    job_id = job["job_id"]
    deadline = time.monotonic() + seconds + 120.0
    while time.monotonic() < deadline:
        job = call("GET", f"/proofs/{job_id}")
        if job["status"] in ("done", "failed", "cancelled"):
            break
        time.sleep(min(1.0, seconds / 4 + 0.2))
    if job["status"] != "done":
        raise EigenError(
            "service_busy",
            f"daemon capture job {job_id} ended {job['status']}: "
            f"{job.get('error')}")
    return {"workload": "daemon", "url": url, "job_id": job_id,
            **(job.get("result") or {})}


# --- report ----------------------------------------------------------------

def fold_prover_stages() -> dict:
    """``ptpu_prover_stage_seconds`` series folded per stage label:
    ``{stage: {count, total_s}}``. The ONE aggregation both the
    ``profile`` report and ``tools/perf_gate.py`` read, so the verb's
    report and the gate's committed baseline cannot drift if the label
    scheme changes."""
    from ..utils import trace

    stages: dict = {}
    for items, s in trace.histogram("prover_stage_seconds").series():
        labels = dict(items)
        key = labels.get("stage", "?")
        entry = stages.setdefault(key, {"count": 0, "total_s": 0.0})
        entry["count"] += s["count"]
        entry["total_s"] += s["sum"]
    return stages


def collect_stage_report(meta: dict, total_wall: float) -> dict:
    """Merge the tracer's per-stage instruments into one report dict:
    prover stages (from ``ptpu_prover_stage_seconds``), the prove/
    converge totals, converge gauges, and compile stats. ``coverage``
    is sum(stage seconds)/prove total — under sync-span mode the stages
    are serialized and exhaustive, so it should sit near 1.0."""
    from ..utils import trace

    stages = fold_prover_stages()
    prove_total = 0.0
    for _, s in trace.histogram("prover_total_seconds").series():
        prove_total += s["sum"]
    converge = {}
    for name in ("converge.edges", "routed.plan_build",
                 "service.operator_build"):
        agg = trace.summary().get(name)
        if agg:
            converge[name] = {"count": agg["count"],
                              "total_s": round(agg["total_s"], 6)}
    sweep = {}
    for items, s in trace.histogram("converge_sweep_seconds").series():
        labels = dict(items)
        sweep[labels.get("backend", "?")] = {
            "sweeps": s["count"],
            "mean_sweep_s": (s["sum"] / s["count"]) if s["count"] else 0.0,
        }
    stage_total = sum(e["total_s"] for e in stages.values())
    coverage = (stage_total / prove_total) if prove_total > 0 else None
    return {
        "schema": "ptpu-profile-v1",
        "meta": meta,
        "total_wall_s": round(total_wall, 6),
        "prove_total_s": round(prove_total, 6),
        "stages": {k: {"count": v["count"],
                       "total_s": round(v["total_s"], 6)}
                   for k, v in sorted(stages.items())},
        "stage_total_s": round(stage_total, 6),
        "coverage": round(coverage, 4) if coverage is not None else None,
        "converge": converge,
        "sweep": sweep,
        "compile": trace.compile_stats(),
        "sync_spans": trace.sync_enabled(),
    }


def print_report(report: dict, out=None) -> None:
    # resolve stdout at CALL time (a def-time default would capture a
    # test harness's swapped-out stream)
    out = out if out is not None else sys.stdout
    meta = report["meta"]
    print(f"profile: workload={meta.get('workload')} "
          f"wall={report['total_wall_s']:.3f}s "
          f"sync_spans={report['sync_spans']}", file=out)
    if report["stages"]:
        width = max(len(s) for s in report["stages"])
        denom = report["prove_total_s"] or report["total_wall_s"]
        print(f"{'stage':<{width}}  {'n':>5}  {'total_s':>9}  "
              f"{'share':>6}", file=out)
        for name, e in sorted(report["stages"].items(),
                              key=lambda kv: -kv[1]["total_s"]):
            share = e["total_s"] / denom if denom else 0.0
            print(f"{name:<{width}}  {e['count']:>5}  "
                  f"{e['total_s']:>9.3f}  {share:>5.1%}", file=out)
        print(f"prove total {report['prove_total_s']:.3f}s, stage sum "
              f"{report['stage_total_s']:.3f}s", file=out)
    for name, e in report["converge"].items():
        print(f"{name}: n={e['count']} total={e['total_s']:.3f}s",
              file=out)
    for backend, e in report["sweep"].items():
        print(f"converge sweeps[{backend}]: {e['sweeps']} observed, "
              f"mean {e['mean_sweep_s'] * 1000:.3f}ms", file=out)
    c = report["compile"]
    print(f"xla: {c['compiles']} compile(s), "
          f"{c['compile_seconds']:.3f}s compiling, "
          f"{c['steady_recompiles']} steady-state recompile(s)",
          file=out)
    if report["coverage"] is not None:
        print(f"STAGE_COVERAGE={report['coverage']:.4f}", file=out)


def handle_profile(args, files, config) -> int:
    """Run the chosen workload under sync-span tracing (+ optional
    xprof capture) and print/write the merged per-stage report."""
    from ..utils import trace
    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    if args.jsonl or not trace.TRACER.enabled:
        # enable() closes any previously-opened stream before swapping
        trace.enable(args.jsonl)
    trace.sync_spans(not args.no_sync)
    trace.install_compile_tracking()
    trace_id = f"profile-{trace.new_id()}"

    def run():
        if args.workload == "prove":
            return run_prove_workload(k=args.k, gates=args.gates,
                                      repeat=args.repeat)
        if args.workload == "refresh":
            return run_refresh_workload(n=args.n, m=args.edges_per_node,
                                        engine=args.engine, tol=args.tol,
                                        repeat=args.repeat)
        if not args.url:
            raise EigenError("config_error",
                             "--workload daemon needs --url (a live "
                             "serve daemon)")
        return run_daemon_capture(args.url, args.seconds)

    # a local capture around the daemon workload would time an HTTP
    # polling loop: the device work (and its xprof log dir) lives on
    # the daemon's side, reported back in the job result
    local_xprof = args.xprof if args.workload != "daemon" else None
    if args.xprof and not local_xprof:
        print("note: --workload daemon captures xprof on the daemon's "
              "filesystem (xprof_dir in the report); local --xprof "
              "ignored", file=sys.stderr)

    t0 = time.perf_counter()
    with trace.context(trace_id=trace_id):
        if local_xprof:
            with trace.device_trace(local_xprof):
                meta = run()
        else:
            meta = run()
    total_wall = time.perf_counter() - t0
    meta["trace_id"] = trace_id
    if local_xprof:
        meta["xprof"] = local_xprof

    report = collect_stage_report(meta, total_wall)
    print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"wrote {args.json}")
    if args.min_coverage:
        if report["coverage"] is None:
            print("error: --min-coverage needs a prove workload "
                  "(no prover total recorded)", file=sys.stderr)
            return 1
        if report["coverage"] < args.min_coverage:
            print(f"error: stage coverage {report['coverage']:.4f} < "
                  f"{args.min_coverage}", file=sys.stderr)
            return 1
    return 0
