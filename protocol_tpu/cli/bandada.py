"""Bandada REST client: threshold-gated Semaphore group membership.

Mirrors ``eigentrust-cli/src/bandada.rs``: POST/DELETE
``{base}/groups/{id}/members/{commitment}`` with the X-API-KEY header
sourced from the BANDADA_API_KEY env var.
"""

from __future__ import annotations

import os
import urllib.request

from ..utils.errors import EigenError


class BandadaApi:
    def __init__(self, base_url: str, api_key: str | None = None):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key or os.environ.get("BANDADA_API_KEY", "")
        if not self.api_key:
            raise EigenError("config_error", "BANDADA_API_KEY is not set")

    def _request(self, method: str, group_id: str, commitment: str) -> None:
        url = f"{self.base_url}/groups/{group_id}/members/{commitment}"
        req = urllib.request.Request(
            url, method=method, headers={"X-API-KEY": self.api_key}
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                if resp.status >= 300:
                    raise EigenError("request_error", f"{method} {url}: {resp.status}")
        except OSError as e:
            raise EigenError("connection_error", f"{method} {url}: {e}") from e

    def add_member(self, group_id: str, commitment: str) -> None:
        self._request("POST", group_id, commitment)

    def remove_member(self, group_id: str, commitment: str) -> None:
        self._request("DELETE", group_id, commitment)
