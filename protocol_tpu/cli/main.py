"""CLI dispatcher: the 15 verbs of the reference CLI plus --backend.

Mirrors ``eigentrust-cli/src/cli.rs`` (Mode enum :78-110 and handlers
:236-678): attest, attestations, bandada, deploy, et-proof,
et-proving-key, et-verify, kzg-params, local-scores, scores, show,
th-proof, th-proving-key, th-verify, update.

Additions over the reference: a ``--backend {native,jax,jax-sparse}`` flag
on the score verbs (the ConvergeBackend seam), a file-persisted local
chain (``node_url = "memory"``) so the full flow runs without an Ethereum
node, and the ``serve`` verb — the long-running trust-scores service
(``protocol_tpu.service``: chain tailer, incremental refresh, proof job
queue, HTTP API) with its durable state store (``protocol_tpu.store``)
maintained by the ``store`` inspect/compact verbs, and the ``scenario``
verb — the adversarial robustness harness (``protocol_tpu.scenarios``). The reference's
handle_update bug (writing ``domain`` into ``as_address``,
cli.rs:639-643) is deliberately not replicated.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..client import (
    AttestationRecord,
    Client,
    ClientConfig,
    CSVFileStorage,
    JSONFileStorage,
    LocalChain,
    ScoreRecord,
)
from ..utils.errors import EigenError
from .fs import EigenFile, assets_dir, load_mnemonic

# Circuit degrees for the EigenTrust4 shape (the reference pins k=20/21,
# circuits/mod.rs:57-59; this stack's ET circuit is 1.85M rows → k=21
# since the GLV shared-doubling ECDSA path, zk/ecdsa_chip.py). The
# Threshold circuit also fits 2^21 (the batched-MSM verifier fold), and
# the flow proves the inner ET snark under the shared TH SRS — one k=21
# SRS now covers both domains (was k=22 with the 272-bit ladders).
ET_PARAMS_K = 21
TH_PARAMS_K = 21


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="protocol-tpu",
        description="TPU-native EigenTrust: attestations, scores, proofs",
    )
    parser.add_argument("--assets", help="assets directory (default ./assets)")
    parser.add_argument(
        "--trace", metavar="PATH",
        help="enable structured tracing; '-' prints a span summary to "
             "stderr, a path additionally streams JSONL there")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("attest", help="sign and publish an attestation")
    p.add_argument("--to", required=True, help="attested peer address (0x..)")
    p.add_argument("--score", required=True, type=int, help="score value 0..255")
    p.add_argument("--message", default="0x" + "00" * 32, help="optional 32-byte message")

    sub.add_parser("attestations", help="fetch attestations into attestations.csv")

    p = sub.add_parser("bandada", help="threshold-gated Bandada group membership")
    p.add_argument("--action", choices=["add", "remove"], required=True)
    p.add_argument("--identity-commitment", required=True)
    p.add_argument("--address", required=True, help="peer address to check")

    sub.add_parser("deploy", help="deploy the AttestationStation (local chain)")

    def _et_shape_args(p):
        p.add_argument("--shape", choices=["default", "tiny"],
                       default="default",
                       help="circuit instantiation: default = the "
                            "EigenTrust4 shape (k=21 params), tiny = "
                            "the 2-peer/2-iteration dev shape (k=20)")

    p = sub.add_parser("et-proof", help="generate the EigenTrust proof")
    _et_shape_args(p)
    p.add_argument("--transcript", choices=["poseidon", "keccak"],
                   default="poseidon",
                   help="keccak emits the on-chain-cheap proof the Yul "
                        "verifier checks at ~388k gas; poseidon keeps "
                        "recursion parity with the aggregator")
    p = sub.add_parser("et-proving-key",
                       help="generate the EigenTrust proving key")
    _et_shape_args(p)
    p = sub.add_parser("et-verify", help="verify the EigenTrust proof")
    _et_shape_args(p)
    p.add_argument("--transcript", choices=["auto", "poseidon", "keccak"],
                   default="auto",
                   help="auto reads et-proof.meta.json (falls back to "
                        "poseidon) so a keccak proof can't be replayed "
                        "under the wrong hash by default")
    p = sub.add_parser(
        "et-verifier",
        help="emit the deployable Yul/EVM verifier (et-verifier.yul)")
    _et_shape_args(p)
    p.add_argument("--transcript", choices=["auto", "poseidon", "keccak"],
                   default="auto",
                   help="auto follows et-proof.meta.json, else keccak "
                        "(the on-chain-cheap variant)")
    p.add_argument("--check", action="store_true",
                   help="replay the written et-proof against the "
                        "generated verifier in the in-repo EVM and "
                        "print the gas")
    p.add_argument("--rpc", metavar="URL",
                   help="deploy the verifier to this JSON-RPC node and "
                        "verify the written et-proof ON-CHAIN via "
                        "eth_call (devnet: client.mocknode)")

    p = sub.add_parser("kzg-params", help="generate KZG params")
    p.add_argument("--k", type=int, required=True, help="circuit degree 2^k rows")

    p = sub.add_parser("local-scores", help="score attestations.csv offline")
    p.add_argument("--backend", choices=["native", "jax", "jax-sparse"], default="native")
    p.add_argument("--batched-ingest", action="store_true",
                   help="recover attestation signers on the device in one batch")

    p = sub.add_parser("scores", help="fetch attestations and compute scores")
    p.add_argument("--backend", choices=["native", "jax", "jax-sparse"], default="native")
    p.add_argument("--batched-ingest", action="store_true",
                   help="recover attestation signers on the device in one batch")

    p = sub.add_parser(
        "serve",
        help="run the long-running trust-scores service (chain tailer, "
             "incremental refresh, proof job queue, HTTP API)")
    p.add_argument("--host", default=None, help="bind host (default "
                   "127.0.0.1; PTPU_SERVE_HOST)")
    p.add_argument("--port", type=int, default=None,
                   help="bind port (0 = ephemeral; default 8799)")
    p.add_argument("--poll-interval", type=float, default=None,
                   help="seconds between chain polls")
    p.add_argument("--tol", type=float, default=None,
                   help="refresh stopping tolerance (relative L1)")
    p.add_argument("--max-iterations", type=int, default=None)
    p.add_argument("--queue-capacity", type=int, default=None,
                   help="proof job backpressure bound (the shedding "
                        "watermark defaults to it)")
    p.add_argument("--workers", type=int, default=None,
                   help="proof pool workers (default 0 = one per jax "
                        "device; host-path workers on a CPU box)")
    p.add_argument("--shard-proves", type=int, default=None,
                   metavar="0|1",
                   help="1: fan a single prove's commit/quotient/fold "
                        "work units out to idle pool workers "
                        "(byte-identical proofs; default 0)")
    p.add_argument("--fabric", type=int, default=None, metavar="0|1",
                   help="1: publish sharded-prove work units under "
                        "<state-dir>/fabric/ so external prove-worker "
                        "processes lend into running proves "
                        "(needs --shard-proves 1 and a state dir; "
                        "default 0)")
    p.add_argument("--fabric-lease-ttl", type=float, default=None,
                   help="seconds an external worker's unit lease "
                        "lives without a heartbeat before the unit "
                        "is reclaimed (default 5)")
    p.add_argument("--shape", choices=["default", "tiny"], default=None,
                   help="circuit shape served by proof jobs")
    p.add_argument("--transcript", choices=["poseidon", "keccak"],
                   default=None, help="default et-proof transcript")
    p.add_argument("--state-dir", default=None,
                   help="durable state store root (attestation WAL, "
                        "graph snapshots, proof artifacts, operator "
                        "cache; default <assets>/service-state) — "
                        "restarts replay it instead of re-fetching "
                        "pre-cursor blocks")
    p.add_argument("--checkpoint-dir", default=None,
                   help="block-cursor checkpoint directory "
                        "(default <state-dir>/cursor)")
    p.add_argument("--follow", default=None, metavar="LEADER_URL",
                   help="run as a READ REPLICA of a leader daemon: "
                        "restore from its /repl/snapshot, tail its "
                        "shipped WAL (/repl/wal), refresh and serve "
                        "/scores //score/<addr> //bundle hermetically "
                        "(no chain tailer, no proof pool; POST /proofs "
                        "answers 503)")

    p = sub.add_parser(
        "prove-worker",
        help="lend this process into a serve --fabric daemon's running "
             "proves: poll the fabric for published work units "
             "(commit MSM batches, quotient row chunks, opening "
             "folds), lease + execute + publish results — "
             "byte-identical placement, lease-reclaim crash safety")
    p.add_argument("--state-dir", default=None,
                   help="the DAEMON's state dir (the fabric lives at "
                        "<state-dir>/fabric; default "
                        "<assets>/service-state) — same-box, "
                        "shared-filesystem mode")
    p.add_argument("--url", default=None,
                   help="daemon base URL (http://host:port) — "
                        "cross-box mode over the /fabric HTTP surface "
                        "instead of a shared filesystem")
    p.add_argument("--name", default=None,
                   help="worker name carried on leases, results and "
                        "the prove.shard spans of units this process "
                        "executes (default fw<pid>)")
    p.add_argument("--poll", type=float, default=0.05,
                   help="seconds between idle fabric polls")
    p.add_argument("--lease-ttl", type=float, default=5.0,
                   help="lease/heartbeat TTL seconds (match the "
                        "daemon's --fabric-lease-ttl)")
    p.add_argument("--max-units", type=int, default=None,
                   help="exit after executing this many units "
                        "(default: run until signalled)")
    p.add_argument("--idle-exit", type=float, default=None,
                   help="exit after this many seconds with no "
                        "claimable unit (default: poll forever)")

    p = sub.add_parser(
        "obs",
        help="observability tooling: validate a JSONL trace stream "
             "(PROTOCOL_TPU_TRACE=<path> / --trace PATH / the serve "
             "daemon's stream) and render its span-aggregate summary "
             "(count/p50/p95 per stage)")
    p.add_argument("path", help="JSONL trace stream to read")
    p.add_argument("--jsonl", action="append", default=[],
                   metavar="PATH", dest="extra_jsonl",
                   help="merge additional JSONL streams into the view "
                        "(repeatable) — e.g. a prove-worker's --trace "
                        "stream joined with the leader's, so one job's "
                        "trace id chains across processes")
    p.add_argument("--follow", action="store_true",
                   help="tail the stream, printing records as they land "
                        "(Ctrl-C to stop)")
    p.add_argument("--trace-id", dest="trace_id",
                   help="print the span/event chain for one trace id "
                        "(attestation digest prefix, job id — including "
                        "its prover-stage spans and the pool worker "
                        "that executed them, request id)")

    p = sub.add_parser(
        "fleet",
        help="fleet observability: render a live leader's /fleet "
             "registry as an operator table — one row per known "
             "instance (leader, followers, prove-workers) with role, "
             "freshness, repl lag and report age; dead instances stay "
             "listed (staleness-honest), flagged inactive")
    p.add_argument("--url", required=True,
                   help="leader daemon base URL (http://host:port)")
    p.add_argument("--json", action="store_true",
                   help="print the raw /fleet JSON instead of the table")

    p = sub.add_parser(
        "slo",
        help="SLO burn rates: render a live daemon's /slo evaluation — "
             "per-objective fast/slow-window burn, in-budget flags and "
             "latched alerts; exits 1 while any alert is latched")
    p.add_argument("--url", required=True,
                   help="daemon base URL (http://host:port) — leader "
                        "or follower (each evaluates its own SLOs)")
    p.add_argument("--json", action="store_true",
                   help="print the raw /slo JSON instead of the table")

    p = sub.add_parser(
        "incident",
        help="incident autopsies: list a live daemon's captured "
             "flight-recorder bundles (/incidents), or render one as a "
             "human-readable autopsy — trigger, burn timeline, top "
             "spans, device cost per compiled plan, named-thread "
             "stacks")
    p.add_argument("--url", required=True,
                   help="daemon base URL (http://host:port) — leader "
                        "or follower (each keeps its own store)")
    p.add_argument("--id", default=None,
                   help="incident id to render (default: list the "
                        "index; 'latest' renders the newest bundle)")
    p.add_argument("--json", action="store_true",
                   help="print the raw JSON instead of the autopsy")

    p = sub.add_parser(
        "profile",
        help="run a workload under sync-span tracing (+ optional xprof "
             "capture) and emit a merged per-stage report")
    p.add_argument("--workload", choices=["prove", "refresh", "daemon"],
                   default="refresh",
                   help="prove: synthetic circuit through prove_auto "
                        "(stage-attributed host or TPU path); refresh: "
                        "synthetic trust-graph converge through the "
                        "ConvergeBackend seam; daemon: capture window "
                        "on a LIVE serve daemon via its job queue")
    p.add_argument("--k", type=int, default=7,
                   help="prove: domain exponent (synthetic circuit)")
    p.add_argument("--gates", type=int, default=64,
                   help="prove: synthetic gate count")
    p.add_argument("--n", type=int, default=2000,
                   help="refresh: peer count")
    p.add_argument("--edges-per-node", type=int, default=4,
                   help="refresh: BA attachment degree")
    p.add_argument("--engine", choices=["gather", "routed"],
                   default="gather", help="refresh: SpMV engine")
    p.add_argument("--tol", type=float, default=1e-6,
                   help="refresh: stopping tolerance")
    p.add_argument("--repeat", type=int, default=1,
                   help="run the workload N times (warm steady-state)")
    p.add_argument("--url", help="daemon: base URL of the live daemon")
    p.add_argument("--seconds", type=float, default=5.0,
                   help="daemon: capture window length")
    p.add_argument("--xprof", metavar="DIR",
                   help="capture a jax.profiler (xprof) device timeline "
                        "into DIR, joinable with the span stream by "
                        "trace id")
    p.add_argument("--jsonl", metavar="PATH",
                   help="stream spans as JSONL to PATH (obs-verb food)")
    p.add_argument("--json", metavar="PATH",
                   help="write the per-stage report as JSON")
    p.add_argument("--no-sync", action="store_true",
                   help="keep async dispatch (production overlap) "
                        "instead of sync-span attribution")
    p.add_argument("--min-coverage", type=float, default=0.0,
                   help="exit 1 unless the named prover stages cover at "
                        "least this fraction of the prove wall time")

    p = sub.add_parser(
        "scenario",
        help="adversarial scenario harness: list topologies, run a "
             "seeded {topology x semiring} robustness experiment "
             "(deterministic JSON), or render a saved report")
    p.add_argument("action", choices=["list", "run", "report"],
                   help="list: topology catalog + knobs; run: one "
                        "seeded run (byte-identical JSON per seed); "
                        "report: human summary of a saved run JSON")
    p.add_argument("--topology", default="sybil-ring",
                   help="attack family (see 'scenario list')")
    p.add_argument("--peers", type=int, default=10_000,
                   help="total peer count (honest + attackers)")
    p.add_argument("--attacker-fraction", type=float, default=0.1,
                   help="fraction of peers controlled by the attacker")
    p.add_argument("--semiring", choices=["plusmul", "maxplus"],
                   default="plusmul",
                   help="sweep algebra: plusmul = EigenTrust mass "
                        "propagation, maxplus = bottleneck (widest-"
                        "path) trust through the same operator")
    p.add_argument("--seed", type=int, default=0,
                   help="topology RNG seed; same seed -> byte-identical "
                        "report")
    p.add_argument("--alpha", type=float, default=0.1,
                   help="pre-trust damping (>0 makes the iteration "
                        "bound spectrum-free)")
    p.add_argument("--tol", type=float, default=1e-6)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--engine", choices=["auto", "sparse", "routed"],
                   default="auto",
                   help="SpMV engine; auto picks routed past 20M edges")
    p.add_argument("--no-baseline", action="store_true",
                   help="skip the attack-free control converge (rank "
                        "displacement then reads as zero)")
    p.add_argument("--timing", action="store_true",
                   help="include wall-clock timing fields (breaks "
                        "byte-identical reproducibility, so opt-in)")
    p.add_argument("--out", help="also write the report JSON here "
                                 "(relative to assets)")
    p.add_argument("--json", help="report: saved run JSON to render")

    sub.add_parser("show", help="print the current config")

    p = sub.add_parser(
        "store",
        help="inspect or compact the serve daemon's durable state store")
    p.add_argument("action", choices=["inspect", "compact"],
                   help="inspect: WAL/snapshot/proof-artifact summary; "
                        "compact: fold latest-wins duplicate "
                        "attestations into a fresh WAL segment "
                        "(run with the daemon stopped)")
    p.add_argument("--state-dir", default=None,
                   help="state store root (default "
                        "<assets>/service-state)")

    p = sub.add_parser(
        "sparse-scores",
        help="converge a raw edge-list trust graph (the scale path)")
    p.add_argument("--edges", required=True,
                   help="CSV of src,dst,weight rows (no header)")
    p.add_argument("--n", type=int, required=True, help="number of peers")
    p.add_argument("--tol", type=float, default=1e-6,
                   help="relative L1 stopping tolerance")
    p.add_argument("--alpha", type=float, default=0.0,
                   help="pre-trust damping factor (0 = reference semantics)")
    p.add_argument("--max-iterations", type=int, default=500)
    p.add_argument("--initial-score", type=float, default=1000.0)
    p.add_argument("--checkpoint-dir",
                   help="run sharded over all devices with chunked "
                        "checkpoint/resume in this directory")
    p.add_argument("--checkpoint-every", type=int, default=25)
    p.add_argument("--engine", choices=["auto", "routed", "gather"],
                   default="auto",
                   help="SpMV engine (single-device and sharded/"
                        "checkpointed runs): 'routed' compiles the edge "
                        "permutation to a Clos lane-shuffle network "
                        "(fastest at scale, one-time plan build; sharded "
                        "runs need a device count dividing 128); 'auto' "
                        "picks it beyond 100K peers when the native "
                        "planner is built")
    p.add_argument("--operator-cache",
                   help="directory for compiled routed operators, keyed "
                        "on the edge-list digest: the one-time routing-"
                        "plan build (minutes at 10M peers) is paid once "
                        "and reused across invocations")
    p.add_argument("--out", default="sparse-scores.csv",
                   help="output CSV (peer_id,score), relative to assets")

    p = sub.add_parser("th-proof", help="generate the Threshold proof")
    p.add_argument("--peer", required=True, help="peer address (0x..)")
    p.add_argument("--threshold", type=int, required=True)
    sub.add_parser("th-proving-key", help="generate the Threshold proving key")
    sub.add_parser("th-verify", help="verify the Threshold proof")

    p = sub.add_parser("update", help="update a config field")
    for fld in ClientConfig.__dataclass_fields__:
        p.add_argument(f"--{fld.replace('_', '-')}", dest=fld)

    return parser


# --- context helpers ------------------------------------------------------


def _load_config(files: EigenFile) -> ClientConfig:
    path = files.config_json()
    if path.exists():
        return ClientConfig.from_dict(JSONFileStorage(path).load())
    return ClientConfig()


def _save_config(files: EigenFile, config: ClientConfig) -> None:
    JSONFileStorage(files.config_json()).save(config.to_dict())


def _make_client(files: EigenFile, config: ClientConfig,
                 batched_ingest: bool = False, shape=None) -> Client:
    chain = None
    if config.node_url == "memory":
        path = files.chain_json()
        if path.exists():
            chain = LocalChain.from_json(JSONFileStorage(path).load())
        else:
            chain = LocalChain()
    kwargs = {}
    if shape is not None:
        kwargs["num_neighbours"] = shape.num_neighbours
        kwargs["num_iterations"] = shape.num_iterations
        kwargs["initial_score"] = shape.initial_score
    return Client(config, load_mnemonic(), chain=chain,
                  batched_ingest=batched_ingest, **kwargs)


def _save_chain(files: EigenFile, client: Client) -> None:
    if isinstance(client.chain, LocalChain):
        JSONFileStorage(files.chain_json()).save(client.chain.to_json())


def _parse_hex(value: str, length: int, what: str) -> bytes:
    try:
        raw = bytes.fromhex(value.removeprefix("0x"))
    except ValueError as e:
        raise EigenError("parsing_error", f"bad {what} (not hex): {value}") from e
    if len(raw) != length:
        raise EigenError("parsing_error", f"bad {what} (need {length} bytes): {value}")
    return raw


def _parse_address(value: str) -> bytes:
    return _parse_hex(value, 20, "address")


def _load_attestations(files: EigenFile) -> list:
    storage = CSVFileStorage(files.attestations_csv(), AttestationRecord)
    return [record.to_signed() for record in storage.load()]


def _fetch_attestations(files: EigenFile, client: Client) -> list:
    atts = client.get_attestations()
    records = [AttestationRecord.from_signed(a) for a in atts]
    CSVFileStorage(files.attestations_csv(), AttestationRecord).save(records)
    return atts


def _write_scores(files: EigenFile, scores: list) -> None:
    records = [ScoreRecord.from_score(s) for s in scores]
    CSVFileStorage(files.scores_csv(), ScoreRecord).save(records)


def _compute_scores(client: Client, atts: list, backend_name: str) -> list:
    """Score through the chosen ConvergeBackend; 'native' is the exact
    reference path, 'jax'/'jax-sparse' run the float path on device and
    are reported alongside the exact rational scores. One circuit setup
    serves both paths (per-attestation ECDSA recovery dominates)."""
    setup = client.et_circuit_setup(atts)
    scores = client.scores_from_setup(setup)
    if backend_name != "native":
        from ..utils.platform import honor_jax_platforms_env

        honor_jax_platforms_env()

        from ..backend import JaxDenseBackend, JaxSparseBackend

        from ..utils import trace

        backend = JaxDenseBackend() if backend_name == "jax" else JaxSparseBackend()
        matrix, _ = setup.opinion
        with trace.span("converge.backend", backend=backend_name):
            float_scores = backend.converge(
                matrix, client.initial_score, client.num_iterations
            )
        for i, score in enumerate(scores):
            ratio = float(score.ratio)
            dev = float(float_scores[i])
            if abs(dev - ratio) > 1e-3 * max(ratio, 1.0):
                raise EigenError(
                    "verification_error",
                    f"backend {backend_name} diverged from the exact path at "
                    f"peer {i}: {dev} vs {ratio}",
                )
    return scores


# --- handlers -------------------------------------------------------------


def handle_attest(args, files, config):
    client = _make_client(files, config)
    tx = client.attest(
        _parse_address(args.to),
        args.score,
        _parse_hex(args.message, 32, "message"),
    )
    _save_chain(files, client)
    print(f"attestation submitted: {tx}")


def handle_attestations(args, files, config):
    client = _make_client(files, config)
    atts = _fetch_attestations(files, client)
    print(f"saved {len(atts)} attestations to {files.attestations_csv()}")


def handle_scores(args, files, config, local: bool):
    client = _make_client(files, config,
                          batched_ingest=getattr(args, "batched_ingest", False))
    atts = _load_attestations(files) if local else _fetch_attestations(files, client)
    scores = _compute_scores(client, atts, args.backend)
    _write_scores(files, scores)
    for s in scores:
        print(f"0x{s.address.hex()}  {float(s.ratio):.6f}")
    print(f"saved {len(scores)} scores to {files.scores_csv()}")


def handle_bandada(args, files, config):
    from .bandada import BandadaApi

    storage = CSVFileStorage(files.scores_csv(), ScoreRecord)
    target = args.address.lower()
    record = next(
        (r for r in storage.load() if r.peer_address.lower() == target), None
    )
    if record is None:
        raise EigenError("validation_error", f"no score for {args.address}")
    threshold = int(config.band_th)
    score = int(record.numerator) // int(record.denominator)
    if args.action == "add":
        if score < threshold:
            raise EigenError(
                "validation_error",
                f"score {score} below band threshold {threshold}",
            )
        BandadaApi(config.band_url).add_member(
            config.band_id, args.identity_commitment
        )
        print(f"added {args.identity_commitment} to group {config.band_id}")
    else:
        BandadaApi(config.band_url).remove_member(
            config.band_id, args.identity_commitment
        )
        print(f"removed {args.identity_commitment} from group {config.band_id}")


def handle_deploy(args, files, config):
    from ..utils.keccak import keccak256

    if config.node_url == "memory":
        address = keccak256(b"protocol_tpu.attestation_station")[12:]
        config.as_address = "0x" + address.hex()
        _save_config(files, config)
        print(f"local AttestationStation at {config.as_address}")
        return
    # live node: sign and send a creation transaction carrying the
    # vendored AttestationStation bytecode (reference: eth.rs:18-25,
    # bytecode att_station.rs:119)
    from ..client.chain import RpcChain
    from ..client.eth import ecdsa_keypairs_from_mnemonic

    keypair = ecdsa_keypairs_from_mnemonic(load_mnemonic(), 1)[0]
    chain = RpcChain.deploy_signed(config.node_url, keypair,
                                   chain_id=int(config.chain_id))
    config.as_address = "0x" + chain.contract_address.hex()
    _save_config(files, config)
    print(f"deployed AttestationStation at {config.as_address}")


def handle_update(args, files, config):
    changed = []
    for fld in ClientConfig.__dataclass_fields__:
        value = getattr(args, fld, None)
        if value is not None:
            setattr(config, fld, int(value) if fld == "chain_id" else value)
            changed.append(fld)
    if not changed:
        raise EigenError("config_error", "no config fields given")
    _save_config(files, config)
    print(f"updated: {', '.join(changed)}")


def handle_show(args, files, config):
    print(json.dumps(config.to_dict(), indent=2))


def handle_kzg_params(args, files, config):
    from ..zk import api as zk

    data = zk.generate_kzg_params(args.k)
    path = files.kzg_params(args.k)
    path.write_bytes(data)
    print(f"wrote {path} ({len(data)} bytes)")


def _et_shape(args):
    """(CircuitShape, params_k) for the --shape flag; "tiny" is the
    2-peer dev instantiation whose 790k rows fit a k=20 SRS."""
    from ..zk.api import DEFAULT_SHAPE, TINY_SHAPE

    if getattr(args, "shape", "default") == "tiny":
        return TINY_SHAPE, 20
    return DEFAULT_SHAPE, ET_PARAMS_K


def handle_et_pk(args, files, config):
    from ..zk import api as zk

    shape, params_k = _et_shape(args)
    params = files.read(files.kzg_params(params_k))
    pk = zk.generate_et_pk(params, shape=shape)
    files.et_proving_key().write_bytes(pk)
    print(f"wrote {files.et_proving_key()}")


def handle_et_proof(args, files, config):
    from ..zk import api as zk

    shape, params_k = _et_shape(args)
    client = _make_client(files, config, shape=shape)
    atts = _load_attestations(files)
    setup = client.et_circuit_setup(atts)
    params = files.read(files.kzg_params(params_k))
    pk = files.read(files.et_proving_key())
    proof = zk.generate_et_proof(params, pk, setup, shape=shape,
                                 transcript=args.transcript)
    files.et_proof().write_bytes(proof)
    files.et_public_inputs().write_bytes(setup.pub_inputs.to_bytes())
    files.et_proof_meta().write_text(
        json.dumps({"transcript": args.transcript}))
    print(f"wrote {files.et_proof()} and {files.et_public_inputs()}")


def _resolve_transcript(args, files, fallback: str) -> str:
    if args.transcript != "auto":
        return args.transcript
    meta = files.et_proof_meta()
    if meta.exists():
        try:
            return json.loads(meta.read_text()).get("transcript", fallback)
        except (ValueError, OSError):
            pass
    return fallback


def handle_et_verify(args, files, config):
    from ..zk import api as zk

    shape, params_k = _et_shape(args)
    transcript = _resolve_transcript(args, files, "poseidon")
    params = files.read(files.kzg_params(params_k))
    pk = files.read(files.et_proving_key())
    proof = files.read(files.et_proof())
    pub_inputs = files.read(files.et_public_inputs())
    ok = zk.verify_et(params, pk, pub_inputs, proof, shape=shape,
                      transcript=transcript)
    print("EigenTrust proof: VALID" if ok else "EigenTrust proof: INVALID")
    return 0 if ok else 1


def handle_et_verifier(args, files, config):
    """Emit the deployable Yul verifier; --check replays the written
    proof artifacts through the in-repo EVM (yellow-paper gas) — the
    full on-chain flow, drivable end-to-end with shipped tools."""
    from ..zk import api as zk

    shape, params_k = _et_shape(args)
    transcript = _resolve_transcript(args, files, "keccak")
    params = files.read(files.kzg_params(params_k))
    pk = files.read(files.et_proving_key())
    code = zk.gen_et_evm_verifier(params, pk, transcript=transcript)
    files.et_verifier().write_text(code)
    print(f"wrote {files.et_verifier()}")
    if getattr(args, "rpc", None):
        # deploy to the node and verify ON-CHAIN over JSON-RPC: the
        # devnet executes the Yul through its EVM (mocknode), so this
        # is the reference's Anvil loop, not a local library replay
        from ..client.chain import VerifierContract
        from ..client.eth import ecdsa_keypairs_from_mnemonic
        from .fs import load_mnemonic

        proof = files.read(files.et_proof())
        pub_inputs = files.read(files.et_public_inputs())
        calldata = zk.et_evm_calldata(pub_inputs, proof, shape=shape)
        kp = ecdsa_keypairs_from_mnemonic(load_mnemonic(), 1)[0]
        contract = VerifierContract.deploy_signed(args.rpc, kp, code)
        ok = contract.verify(calldata)
        gas = contract.estimate_gas(calldata) if ok else 0
        print(f"on-chain verify at 0x{contract.address.hex()}: "
              f"{'VALID' if ok else 'INVALID'} ({gas} gas incl. tx, "
              f"{transcript} transcript)")
        return 0 if ok else 1
    if args.check:
        from ..zk.yul import VMRevert, YulVM

        proof = files.read(files.et_proof())
        pub_inputs = files.read(files.et_public_inputs())
        calldata = zk.et_evm_calldata(pub_inputs, proof, shape=shape)
        try:
            out, gas = YulVM(code).run(calldata)
            ok = int.from_bytes(out, "big") == 1
        except VMRevert:
            ok, gas = False, 0
        print(f"EVM replay: {'VALID' if ok else 'INVALID'} "
              f"({gas} gas, {transcript} transcript)")
        return 0 if ok else 1


def handle_th_pk(args, files, config):
    import os

    from ..zk import api as zk

    # persist the dummy inner-ET snark next to the other artifacts so a
    # re-run of th-pk (or a th-proof after it) skips the duplicate
    # inner keygen/prove (zk/api.py inner-ET caches)
    os.environ.setdefault("PTPU_TH_CACHE_DIR", str(files.assets))
    params = files.read(files.kzg_params(TH_PARAMS_K))
    pk = zk.generate_th_pk(params)
    files.th_proving_key().write_bytes(pk)
    print(f"wrote {files.th_proving_key()}")


def handle_th_proof(args, files, config):
    from ..zk import api as zk

    client = _make_client(files, config)
    atts = _load_attestations(files)
    setup = client.th_circuit_setup(
        atts, _parse_address(args.peer), args.threshold
    )
    params = files.read(files.kzg_params(TH_PARAMS_K))
    pk = files.read(files.th_proving_key())
    proof = zk.generate_th_proof(params, pk, setup)
    files.th_proof().write_bytes(proof)
    files.th_public_inputs().write_bytes(setup.pub_inputs.to_bytes())
    print(f"wrote {files.th_proof()} and {files.th_public_inputs()}")


def handle_th_verify(args, files, config):
    from ..zk import api as zk

    params = files.read(files.kzg_params(TH_PARAMS_K))
    pk = files.read(files.th_proving_key())
    proof = files.read(files.th_proof())
    pub_inputs = files.read(files.th_public_inputs())
    ok = zk.verify_th(params, pk, pub_inputs, proof)
    print("Threshold proof: VALID" if ok else "Threshold proof: INVALID")
    return 0 if ok else 1


def handle_sparse_scores(args, files, config):
    """The north-star scale path from the command line: edge list in,
    converged scores out, optionally sharded + checkpointed."""
    import csv

    import numpy as np

    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from pathlib import Path

    edges_path = Path(args.edges)
    if not edges_path.is_absolute():
        edges_path = files.assets / edges_path
    src_l, dst_l, val_l = [], [], []
    try:
        with open(edges_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                src_l.append(int(row[0]))
                dst_l.append(int(row[1]))
                val_l.append(float(row[2]) if len(row) > 2 else 1.0)
    except (OSError, ValueError, IndexError) as e:
        raise EigenError("file_io_error", f"bad edge list: {e}") from e
    if not src_l:
        raise EigenError("validation_error", "edge list is empty")
    src = np.asarray(src_l)
    dst = np.asarray(dst_l)
    val = np.asarray(val_l)
    if (src.min() < 0 or dst.min() < 0
            or src.max() >= args.n or dst.max() >= args.n):
        raise EigenError("validation_error",
                         f"edge endpoints must be in [0, {args.n})")

    from ..utils import trace

    def _operator_cache_path(kind, num_shards):
        """Cache key = digest of the exact edge list + build geometry, so
        a changed graph can never load a stale plan."""
        if not args.operator_cache:
            return None
        import hashlib

        h = hashlib.sha256()
        h.update(f"{kind}:v1:n={args.n}:D={num_shards}".encode())
        for a in (src, dst, val):
            h.update(np.ascontiguousarray(a).tobytes())
        cache_dir = Path(args.operator_cache)
        if not cache_dir.is_absolute():
            cache_dir = files.assets / cache_dir
        cache_dir.mkdir(parents=True, exist_ok=True)
        return cache_dir / f"{kind}_{h.hexdigest()[:24]}.npz"

    def _cached_operator(cache_path, load_fn, build_fn):
        """Load the compiled operator from the cache, else build and
        cache it. A corrupt/stale entry must never brick the run —
        warn, rebuild, overwrite."""
        if cache_path is not None and cache_path.exists():
            try:
                with trace.span("cli.operator_load", path=str(cache_path)):
                    return load_fn(cache_path)
            except Exception as e:
                print(f"warning: ignoring unreadable operator cache "
                      f"{cache_path}: {e}", file=sys.stderr)
        op = build_fn()
        if cache_path is not None:
            op.save(cache_path)
        return op

    if args.checkpoint_dir:
        import jax
        import jax.numpy as jnp

        from ..parallel import (
            build_sharded_operator,
            build_sharded_routed_operator,
            make_mesh,
            sharded_converge_checkpointed,
        )
        from ..utils.checkpoint import CheckpointManager

        ck_dir = Path(args.checkpoint_dir)
        if not ck_dir.is_absolute():
            ck_dir = files.assets / ck_dir
        n_dev = len(jax.devices())
        mesh = make_mesh(n_dev)
        engine = args.engine
        if engine == "auto":
            from .. import native as pn

            engine = ("routed" if args.n >= 100_000 and pn.available()
                      and 128 % n_dev == 0 else "gather")
        if engine == "routed" and 128 % n_dev != 0:
            raise EigenError(
                "validation_error",
                f"routed engine needs a device count dividing 128, "
                f"have {n_dev}")
        if engine == "routed":
            from ..parallel.routed import ShardedRoutedOperator

            sop = _cached_operator(
                _operator_cache_path("sharded_routed", n_dev),
                lambda p: ShardedRoutedOperator.load(p, num_shards=n_dev),
                lambda: build_sharded_routed_operator(args.n, src, dst, val,
                                                      num_shards=n_dev))
            s0 = jnp.asarray(sop.initial_scores(
                args.initial_score, dtype=np.float32))
        else:
            sop = build_sharded_operator(args.n, src, dst, val,
                                         num_shards=n_dev)
            s0 = sop.initial_scores(args.initial_score, dtype=jnp.float32)
        try:
            with trace.span("cli.sparse_scores", mode="sharded", n=args.n,
                            engine=engine):
                scores, iters, delta = sharded_converge_checkpointed(
                    sop, s0, mesh, CheckpointManager(str(ck_dir)),
                    tol=args.tol, max_iterations=args.max_iterations,
                    alpha=args.alpha,
                    checkpoint_every=args.checkpoint_every,
                )
        except ValueError as e:
            # bad checkpoint_every / stale-checkpoint mismatch on resume
            raise EigenError("validation_error", str(e)) from e
        if engine == "routed":
            scores = sop.scores_for_nodes(np.asarray(scores))
        else:
            scores = np.asarray(scores)[: args.n]
    else:
        from ..backend import JaxRoutedBackend, JaxSparseBackend

        engine = args.engine
        if engine == "auto":
            from .. import native as pn

            engine = ("routed" if args.n >= 100_000 and pn.available()
                      else "gather")
        backend = (JaxRoutedBackend() if engine == "routed"
                   else JaxSparseBackend())
        valid = np.ones(args.n, dtype=bool)
        extra = {}
        if engine == "routed":
            from ..ops.routed import RoutedOperator, build_routed_operator

            cache_path = _operator_cache_path("routed", 1)
            if cache_path is not None:
                extra["operator"] = _cached_operator(
                    cache_path, RoutedOperator.load,
                    lambda: build_routed_operator(args.n, src, dst, val,
                                                  valid))
        with trace.span("cli.sparse_scores", mode="single", n=args.n,
                        engine=engine):
            scores, iters, delta = backend.converge_edges(
                args.n, src, dst, val, valid, args.initial_score,
                args.max_iterations, tol=args.tol, alpha=args.alpha,
                **extra,
            )

    out_path = Path(args.out)
    if not out_path.is_absolute():
        out_path = files.assets / out_path
    with open(out_path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["peer_id", "score"])
        for i, s in enumerate(np.asarray(scores)[: args.n]):
            writer.writerow([i, repr(float(s))])
    converged = delta <= args.tol
    print(f"{args.n} peers, {len(src)} edges: "
          f"{'converged' if converged else 'NOT converged'} after "
          f"{int(iters)} iterations (delta {float(delta):.2e})")
    print(f"saved {out_path}")
    return 0 if converged else 1


def handle_serve(args, files, config):
    """Boot the long-running service (protocol_tpu.service) against the
    configured chain and block until SIGTERM/SIGINT drains it."""
    from pathlib import Path

    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from ..service import ServiceConfig, TrustService

    svc_config = ServiceConfig.from_env(
        host=args.host, port=args.port,
        poll_interval=args.poll_interval, tol=args.tol,
        max_iterations=args.max_iterations,
        queue_capacity=args.queue_capacity,
        pool_workers=args.workers,
        shard_proves=args.shard_proves,
        fabric=args.fabric, fabric_lease_ttl=args.fabric_lease_ttl,
        proof_shape=args.shape, transcript=args.transcript,
        state_dir=args.state_dir, follow=args.follow)
    if svc_config.state_dir:
        state_dir = Path(svc_config.state_dir)
        if not state_dir.is_absolute():
            state_dir = files.assets / state_dir
    else:
        state_dir = files.service_state_dir()
    if svc_config.follow:
        # follower replica: no chain client at all — the leader's
        # shipped WAL is the only upstream. The domain comes from the
        # same config the leader reads, so records decode identically.
        from ..service.follower import FollowerService

        domain = bytes.fromhex(config.domain.removeprefix("0x"))
        follower = FollowerService(
            svc_config.follow, domain, svc_config, str(state_dir),
            batched_ingest=None)
        url = follower.start()
        follower.install_signal_handlers()
        print(f"trust-scores FOLLOWER listening on {url} "
              f"(leader: {svc_config.follow}, state: {state_dir}, "
              f"peers: {follower.graph.n}); SIGTERM drains",
              flush=True)
        follower.wait()
        if follower.drain_clean:
            print("follower drained", flush=True)
            return 0
        print("follower drained UNCLEAN (timeout or persist failure)",
              flush=True)
        return 1
    if args.checkpoint_dir:
        ck_dir = Path(args.checkpoint_dir)
        if not ck_dir.is_absolute():
            ck_dir = files.assets / ck_dir
    else:
        # always under the state dir. A pre-store deployment (cursor in
        # assets/service-cursor, graph memory-only) deliberately does
        # NOT resume that cursor: its pre-cursor attestations were never
        # persisted, so resuming would lose them forever — re-tailing
        # from 0 once rebuilds everything into the WAL (get_logs is
        # idempotent, edges are latest-wins, the log dedups by content)
        ck_dir = state_dir / "cursor"
    # batched_ingest=None → the Client's auto rule (batched signer
    # recovery on an accelerator from 32 lanes up); the batch verbs'
    # False default would pin the daemon to scalar recovery forever
    client = _make_client(files, config, batched_ingest=None)
    if config.node_url == "memory":
        # tail the file-persisted local chain so attest runs from OTHER
        # processes are visible (the in-memory LocalChain a fresh Client
        # builds would be a frozen snapshot)
        from ..service.tailer import FileBackedLocalChain

        client.chain = FileBackedLocalChain(files.chain_json())
    service = TrustService(client, svc_config, str(ck_dir), files=files,
                           state_dir=str(state_dir))
    url = service.start()
    service.install_signal_handlers()
    replayed = service.store.replayed_records if service.store else 0
    print(f"trust-scores service listening on {url} "
          f"(chain: {config.node_url}, cursor: {service.tailer.cursor}, "
          f"state: {state_dir}, replayed: {replayed}); "
          "SIGTERM drains", flush=True)
    service.wait()
    if service.drain_clean:
        print("service drained", flush=True)
        return 0
    # an overrun drain budget / cursor persist failure must surface to
    # the supervisor (systemd restart-on-failure, the smoke's rc check)
    print("service drained UNCLEAN (timeout or persist failure)",
          flush=True)
    return 1


def handle_prove_worker(args, files, config):
    """Run one external fabric worker process (the worker half of
    ``serve --fabric``): poll, lease, execute, publish — until
    ``--max-units`` / ``--idle-exit`` / SIGINT/SIGTERM."""
    import os as _os
    import signal
    import threading
    from pathlib import Path

    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from ..zk.fabric import FabricStore, RemoteFabric, run_worker

    name = args.name or f"fw{_os.getpid()}"
    if args.url:
        fabric = RemoteFabric(args.url)
        fabric.lease_ttl = args.lease_ttl
        where = args.url
    else:
        if args.state_dir:
            state_dir = Path(args.state_dir)
            if not state_dir.is_absolute():
                state_dir = files.assets / state_dir
        else:
            state_dir = files.service_state_dir()
        from ..service.faults import FaultInjector

        root = Path(state_dir) / "fabric"
        # env-gated fault injection (PTPU_FAULT_DISK): the lease-expiry
        # fault test tears THIS process's result writes — production
        # runs with the env unset pay nothing
        fabric = FabricStore(str(root), lease_ttl=args.lease_ttl,
                             faults=FaultInjector())
        where = str(root)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except (ValueError, OSError):  # non-main thread / platform
            pass
    # fleet observability: workers used to emit NOTHING — now every
    # span/event carries instance/role, ptpu_build_info is up from the
    # first scrape, and a telemetry pusher ships the instrument state
    # + recent span window to the leader (HTTP in --url mode, atomic
    # file drop under <state-dir>/fabric/telemetry otherwise — the
    # leader's observer thread sweeps the drop dir)
    from ..service.telemetry import TelemetryPusher, set_build_info
    from ..utils import trace as _trace

    if not _trace.TRACER.enabled:
        _trace.enable()  # in-memory: telemetry needs the instruments
    set_build_info(name, "prove-worker")
    telemetry_interval = float(
        _os.environ.get("PTPU_SERVE_TELEMETRY_INTERVAL", "2.0") or 2.0)
    target = args.url if args.url else str(Path(where) / "telemetry")
    pusher = TelemetryPusher(
        target, name, "prove-worker", interval=telemetry_interval,
        summary=lambda: {"polling": where,
                         "lease_ttl": args.lease_ttl})
    threading.Thread(target=pusher.run, args=(stop,), daemon=True,
                     name="ptpu-telemetry").start()
    # stall watchdog (no incident store — the worker's gauges ship to
    # the leader via telemetry, where the fleet-wide SLO path pages):
    # the worker loop heartbeats, a wedged native call ages it out
    import functools

    from ..service.watchdog import Heartbeats, StallWatchdog

    beats = Heartbeats()
    loop_name = f"ptpu-worker-{name}"
    beats.register(loop_name)
    watchdog = StallWatchdog(
        beats,
        stall_after=float(_os.environ.get(
            "PTPU_SERVE_WATCHDOG_STALL_AFTER", "30") or 30))
    watchdog.start()
    print(f"prove-worker {name} polling {where} "
          f"(lease ttl {args.lease_ttl:g}s)", flush=True)
    executed = run_worker(fabric, name, poll=args.poll,
                          lease_ttl=args.lease_ttl,
                          max_units=args.max_units,
                          idle_exit=args.idle_exit, stop=stop,
                          beat=functools.partial(beats.beat, loop_name))
    stop.set()
    watchdog.stop()
    # one farewell push so the final units' spans/instruments ship
    # even on a quick exit (best-effort, like every push)
    pusher.push_once()
    print(f"prove-worker {name} exiting after {executed} units",
          flush=True)
    return 0


def handle_obs(args, files, config):
    """Offline observability: parse + validate a JSONL trace stream
    (the ``PROTOCOL_TPU_TRACE`` / ``serve`` daemon output), render the
    span-aggregate summary table, optionally follow the stream or print
    one trace id's end-to-end chain. Exit 1 when invalid records were
    seen — the stream is a machine-readable contract, not best-effort
    logging."""
    import time as _time
    from collections import deque

    from ..utils.trace import validate_record

    def parse(line, lineno, invalid):
        line = line.strip()
        if not line:
            return None
        try:
            obj = json.loads(line)
        except ValueError:
            invalid.append(f"line {lineno}: not JSON")
            return None
        err = validate_record(obj)
        if err is not None:
            invalid.append(f"line {lineno}: {err}")
            return None
        return obj

    def matches(obj, trace_id):
        return (obj.get("trace_id") == trace_id
                or trace_id in (obj.get("trace_ids") or ()))

    invalid: list = []
    agg: dict = {}
    durations: dict = {}  # per-stage duration samples for p50/p95
    counts = {"span": 0, "event": 0, "metric": 0}
    chain: list = []

    def ingest(obj) -> None:
        counts[obj["type"]] += 1
        if obj["type"] == "span":
            a = agg.setdefault(obj["name"],
                               {"count": 0, "total_s": 0.0,
                                "max_s": 0.0})
            a["count"] += 1
            a["total_s"] += obj["duration_s"]
            a["max_s"] = max(a["max_s"], obj["duration_s"])
            # bounded per-name sample window for the percentile
            # columns (a daemon stream can hold millions of spans;
            # deque(maxlen) keeps the append O(1))
            if obj["name"] not in durations:
                durations[obj["name"]] = deque(maxlen=16384)
            durations[obj["name"]].append(obj["duration_s"])
        if args.trace_id and matches(obj, args.trace_id):
            chain.append(obj)

    # size-rotation awareness (PTPU_TRACE_MAX_BYTES): a stream's `.1`
    # sibling holds the OLDER records — fold it in first so aggregates
    # cover the whole history and chains stay whole across a rotation
    import os as _os

    def _with_rotated(path: str) -> list:
        sib = path + ".1"
        return [sib, path] if _os.path.exists(sib) else [path]

    # merged streams (--jsonl, repeatable): other processes' trace
    # files fold into the same aggregate + chain view — the
    # cross-process trace join (worker spans carry instance/role);
    # the main stream's rotated sibling rides this loop too
    extra_streams = [p for e in args.extra_jsonl
                     for p in _with_rotated(e)]
    extra_streams += _with_rotated(args.path)[:-1]
    for extra in extra_streams:
        try:
            ef = open(extra)
        except OSError as e:
            raise EigenError("file_io_error",
                             f"cannot open trace stream: {e}") from e
        with ef:
            e_lineno = 0
            for line in ef:
                e_lineno += 1
                before = len(invalid)
                obj = parse(line, e_lineno, invalid)
                if obj is None:
                    if len(invalid) > before:
                        invalid[-1] = f"{extra} {invalid[-1]}"
                    continue
                ingest(obj)
    try:
        f = open(args.path)
    except OSError as e:
        raise EigenError("file_io_error",
                         f"cannot open trace stream: {e}") from e
    with f:
        lineno = 0
        for line in f:
            lineno += 1
            obj = parse(line, lineno, invalid)
            if obj is None:
                continue
            ingest(obj)

        shown = ", ".join([args.path, *args.extra_jsonl])
        print(f"{shown}: {counts['span']} span(s), "
              f"{counts['event']} event(s), {counts['metric']} "
              f"metric(s), {len(invalid)} invalid record(s)")
        for msg in invalid[:20]:
            print(f"  invalid: {msg}", file=sys.stderr)
        if agg:
            from ..utils.trace import percentile

            width = max(len(n) for n in agg)
            print(f"{'span':<{width}}  {'n':>8}  {'total_s':>10}  "
                  f"{'mean_ms':>9}  {'p50_ms':>9}  {'p95_ms':>9}  "
                  f"{'max_s':>9}")
            for name, a in sorted(agg.items(),
                                  key=lambda kv: -kv[1]["total_s"]):
                mean_ms = 1000.0 * a["total_s"] / a["count"]
                # agg and durations are filled in lockstep in the span
                # branch above, so the window is always present
                d = durations[name]
                p50_ms = 1000.0 * percentile(d, 0.50)
                p95_ms = 1000.0 * percentile(d, 0.95)
                print(f"{name:<{width}}  {a['count']:>8}  "
                      f"{a['total_s']:>10.3f}  {mean_ms:>9.3f}  "
                      f"{p50_ms:>9.3f}  {p95_ms:>9.3f}  "
                      f"{a['max_s']:>9.3f}")
        if args.trace_id:
            print(f"\ntrace {args.trace_id}: {len(chain)} record(s)")
            for obj in sorted(chain, key=lambda o: o.get("ts", 0.0)):
                dur = (f" {obj['duration_s'] * 1000:.3f}ms"
                       if obj["type"] == "span" else "")
                ids = ""
                if obj["type"] == "span":
                    ids = (f" span={obj.get('span_id', '?')}"
                           + (f" parent={obj['parent_id']}"
                              if obj.get("parent_id") else ""))
                # pool-worker attribution: which worker executed a
                # proof job's prover stages
                who = (f" worker={obj['worker']}"
                       if obj.get("worker") else "")
                # fleet attribution: which PROCESS emitted the record
                # (merged streams / shipped span windows carry it)
                inst = (f" instance={obj['instance']}"
                        if obj.get("instance") else "")
                rem = " remote=1" if obj.get("remote") else ""
                print(f"  {obj.get('ts', 0.0):.6f} {obj['type']:<6} "
                      f"{obj['name']}{dur}{ids}{who}{inst}{rem}")

        if args.follow:
            print("following (Ctrl-C to stop)...", file=sys.stderr)
            try:
                while True:
                    line = f.readline()
                    if not line:
                        _time.sleep(0.2)
                        continue
                    lineno += 1
                    if not line.strip():
                        continue  # blank: skipped, not invalid
                    before = len(invalid)
                    obj = parse(line, lineno, invalid)
                    if obj is None:
                        if len(invalid) > before:
                            print(f"  invalid: {invalid[-1]}",
                                  file=sys.stderr)
                        continue
                    if args.trace_id and not matches(obj, args.trace_id):
                        continue
                    print(json.dumps(obj), flush=True)
            except KeyboardInterrupt:
                pass
    return 1 if invalid else 0


def handle_store(args, files, config):
    """Offline maintenance of the serve daemon's state store: a
    human-readable summary (``inspect``) and latest-wins WAL compaction
    (``compact`` — duplicates folded by recovered (signer, about) key,
    the chain store's own identity)."""
    from pathlib import Path

    from ..store import AttestationWAL, ProofArtifactStore

    if args.state_dir:
        state_dir = Path(args.state_dir)
        if not state_dir.is_absolute():
            state_dir = files.assets / state_dir
    else:
        state_dir = files.service_state_dir()
    wal_dir = str(state_dir / "wal")

    if args.action == "inspect":
        # inspection must not mutate — and must be safe against a LIVE
        # daemon: readonly WAL scan, sweep-free snapshot listing, and
        # no directory creation anywhere
        from ..store.snapshot import list_steps_readonly, read_meta_readonly

        wal = AttestationWAL(wal_dir, readonly=True)
        records = sum(1 for _ in wal.replay())
        stats = wal.stats()
        print(f"state dir: {state_dir}")
        print(f"wal: {stats['segments']} segment(s), {stats['bytes']} "
              f"bytes, {records} intact record(s), "
              f"{stats['torn_skipped']} torn/corrupt scan stop(s)")
        snap_dir = str(state_dir / "snapshots")
        steps = list_steps_readonly(snap_dir)
        if steps:
            meta = read_meta_readonly(snap_dir, steps[-1]) or {}
            print(f"snapshots: {len(steps)} (latest revision "
                  f"{meta.get('revision')}, "
                  f"{meta.get('n_attestations')} attestation(s), "
                  f"wal position {meta.get('wal_segment')}:"
                  f"{meta.get('wal_offset')})")
        else:
            print("snapshots: none")
        # a CLI-launched daemon persists artifacts into the EigenFile
        # assets layout (handle_serve passes files=); state_dir/proofs
        # is the embedded/provers-injected fallback — report whichever
        # actually exists
        proofs_dir = files.proofs_dir()
        if not proofs_dir.is_dir():
            proofs_dir = state_dir / "proofs"
        n_proofs = (ProofArtifactStore(str(proofs_dir)).count()
                    if proofs_dir.is_dir() else 0)
        print(f"proof artifacts: {n_proofs} ({proofs_dir})")
        return 0

    # compact: fold by the chain store's identity — (creator, about) —
    # recovering each record's signer the way replay would; records that
    # fail recovery are dropped (replay rejects them anyway)
    from ..client.attestation import DOMAIN_PREFIX, SignedAttestationData
    from ..client.eth import address_from_public_key

    domain = bytes.fromhex(config.domain.removeprefix("0x"))
    key = DOMAIN_PREFIX + domain

    def fold_key(block, about, payload):
        try:
            signed = SignedAttestationData.from_log(about, key, payload)
            signer = address_from_public_key(signed.recover_public_key())
        except (EigenError, ValueError):
            return None
        return signer, about

    from ..store.state_store import acquire_state_lock

    lock = acquire_state_lock(str(state_dir))  # refuse a live daemon
    try:
        wal = AttestationWAL(wal_dir)
        out = wal.compact(fold_key)
        wal.close()
    finally:
        if lock is not None:
            lock.close()
    print(f"compacted: {out['records_in']} record(s) -> "
          f"{out['records_out']} in segment {out['segment']} "
          f"({out['dropped']} unrecoverable dropped, "
          f"{out['segments_removed']} old segment(s) removed)")
    return 0


def _fetch_json(url: str, path: str, timeout: float = 10.0):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url.rstrip("/") + path,
                                    timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise EigenError("network_error",
                         f"cannot fetch {path} from {url}: {e}") from e


def _fmt_cell(value, unit: str = "") -> str:
    if value is None:
        return "-"  # no data (pre-publish sentinel): honest, not -1
    if isinstance(value, float):
        return f"{value:.2f}{unit}"
    return f"{value}{unit}"


def handle_fleet(args, files, config):
    """Render a leader's /fleet registry as an operator table: one
    row per known instance, dead ones flagged, never dropped."""
    fleet = _fetch_json(args.url, "/fleet")
    if args.json:
        print(json.dumps(fleet, indent=2))
        return 0
    rows = fleet.get("instances", [])
    counts = fleet.get("counts", {})
    print(f"fleet @ {args.url}: {counts.get('active', 0)}/"
          f"{counts.get('total', 0)} active "
          f"(ttl {fleet.get('ttl_seconds', 0):g}s) "
          f"roles={counts.get('by_role', {})}")
    width = max([len(r.get("instance", "")) for r in rows] + [8])
    print(f"{'instance':<{width}}  {'role':<12} {'up':<4} "
          f"{'report_age':>10}  {'freshness':>9}  {'repl_lag':>8}")
    for r in rows:
        print(f"{r.get('instance', '?'):<{width}}  "
              f"{r.get('role', '?'):<12} "
              f"{'up' if r.get('active') else 'DEAD':<4} "
              f"{_fmt_cell(r.get('report_age_seconds'), 's'):>10}  "
              f"{_fmt_cell(r.get('score_freshness_seconds'), 's'):>9}  "
              f"{_fmt_cell(r.get('repl_lag_seconds'), 's'):>8}")
    return 0


def handle_slo(args, files, config):
    """Render a daemon's /slo evaluation; exit 1 while any alert is
    latched (scriptable: the smoke and a pager check share it)."""
    slo = _fetch_json(args.url, "/slo")
    if args.json:
        print(json.dumps(slo, indent=2))
        return 1 if slo.get("alerting") else 0
    rows = slo.get("slos", [])
    print(f"slo @ {args.url}: {len(rows)} objective(s), "
          f"alerts={slo.get('alerts', [])}")
    if rows:
        width = max(len(r.get("slo", "")) for r in rows)
        print(f"{'slo':<{width}}  {'objective':>9}  {'fast_burn':>9}  "
              f"{'slow_burn':>9}  {'budget':<10} {'alert':<5}")
        for r in rows:
            burn = r.get("burn", {})
            print(f"{r.get('slo', '?'):<{width}}  "
                  f"{r.get('objective', 0.0):>9.3f}  "
                  f"{burn.get('fast', 0.0):>9.3f}  "
                  f"{burn.get('slow', 0.0):>9.3f}  "
                  f"{'in-budget' if r.get('in_budget') else 'BURNING':<10} "
                  f"{'YES' if r.get('alerting') else 'no':<5}")
    return 1 if slo.get("alerting") else 0


def handle_incident(args, files, config):
    """List a daemon's incident bundles, or render one as the
    human-readable autopsy (``service/recorder.py::render_autopsy``)."""
    if args.id is None:
        index = _fetch_json(args.url, "/incidents")
        if args.json:
            print(json.dumps(index, indent=2))
            return 0
        rows = index.get("incidents", [])
        print(f"incidents @ {args.url}: {len(rows)} bundle(s)")
        for r in rows:
            import time as _time

            ts = r.get("captured_at")
            when = (_time.strftime("%Y-%m-%d %H:%M:%S",
                                   _time.localtime(ts)) if ts else "?")
            print(f"  {r.get('id', '?')}  {when}  "
                  f"[{r.get('trigger', '?')}] {r.get('reason', '')}")
        return 0
    inc_id = args.id
    if inc_id == "latest":
        rows = _fetch_json(args.url, "/incidents").get("incidents", [])
        if not rows:
            print("no incidents captured", file=sys.stderr)
            return 1
        inc_id = rows[-1]["id"]
    bundle = _fetch_json(args.url, f"/incidents/{inc_id}")
    if args.json:
        print(json.dumps(bundle, indent=2))
        return 0
    from ..service.recorder import render_autopsy

    print(render_autopsy(bundle), end="")
    return 0


def handle_profile(args, files, config):
    from .profilecmd import handle_profile as _handle

    return _handle(args, files, config)


def handle_scenario(args, files, config):
    from .scenariocmd import handle_scenario as _handle

    return _handle(args, files, config)


HANDLERS = {
    "attest": handle_attest,
    "serve": handle_serve,
    "profile": handle_profile,
    "attestations": handle_attestations,
    "bandada": handle_bandada,
    "deploy": handle_deploy,
    "et-proof": handle_et_proof,
    "et-verifier": handle_et_verifier,
    "et-proving-key": handle_et_pk,
    "et-verify": handle_et_verify,
    "fleet": handle_fleet,
    "incident": handle_incident,
    "kzg-params": handle_kzg_params,
    "obs": handle_obs,
    "slo": handle_slo,
    "prove-worker": handle_prove_worker,
    "scenario": handle_scenario,
    "show": handle_show,
    "sparse-scores": handle_sparse_scores,
    "store": handle_store,
    "th-proof": handle_th_proof,
    "th-proving-key": handle_th_pk,
    "th-verify": handle_th_verify,
    "update": handle_update,
}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    files = EigenFile(assets_dir(args.assets))
    config = _load_config(files)
    if args.trace:
        from ..utils import trace

        try:
            trace.enable(None if args.trace == "-" else args.trace)
        except OSError as e:
            print(f"error: cannot open trace path: {e}", file=sys.stderr)
            return 1
    try:
        if args.command == "scores":
            return handle_scores(args, files, config, local=False) or 0
        if args.command == "local-scores":
            return handle_scores(args, files, config, local=True) or 0
        return HANDLERS[args.command](args, files, config) or 0
    except EigenError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        if args.trace:
            from ..utils import trace

            for name, agg in sorted(trace.summary().items()):
                print(f"trace: {name}  n={agg['count']}  "
                      f"total={agg['total_s']:.3f}s  max={agg['max_s']:.3f}s",
                      file=sys.stderr)
            # the tracer is process-global: close the stream and clear
            # state so in-process callers don't leak spans across runs
            trace.disable()
            trace.TRACER.reset()
