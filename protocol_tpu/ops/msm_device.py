"""Sorted-prefix device MSM — the executable skeleton behind the kill.

Round-3 asked for the PLONK commitment MSMs on the TPU; the committed
chip probes (``tools/probe_msm_prims.py``, ``PROBES_r05.json``) killed
the design honestly: the VPU's emulated int32 multiply tops out at
~44 M field-muls/s and a Pippenger bucket pass is irreducibly ~16n
elementwise EC adds ≈ 5-9 s per 2^20 MSM — strictly worse than the
host's ~4 s AVX-512 IFMA MSM (BASELINE.md "Why the MSM stays on the
host"). This module keeps the DESIGN runnable rather than prose-only
(VERDICT r4 → r5 ask #8): the day hardware with native 32-bit multiply
or faster gathers shows up, the kill can be re-litigated by running
``tests/test_msm_device.py`` (skip-marked) instead of re-deriving the
kernel from a BASELINE paragraph.

Pipeline per window (the probe-informed shape — ``lax.sort`` runs at
~HBM speed even with wide payloads, so one sort replaces the
scalar-core gather storm a bucket scatter would be):

1. window digits of every scalar;
2. argsort by digit + take — the fused sort+gather;
3. segmented Hillis-Steele inclusive scan of the SORTED points under
   the branchless Jacobian group law (log2 n batched adds);
4. segment tails are the bucket sums; a tiny 2^c suffix-sum telescope
   yields Σ d·S_d (the Pippenger triangle trick);
5. windows combine MSB→LSB with c doublings + one add.

Exact integer arithmetic end to end on the modulus-generic limb engine
(``ops.fieldops``); the Jacobian kernels are the batched a=0 group law
shared with the secp256k1 ingest ladder (``ops.secp_batch`` — BN254 G1
is y² = x³ + 3). Bit-exact vs the host ``zk.bn254.g1_msm`` oracle.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.fields import BN254_FQ_MODULUS
from .fieldops import (
    NUM_LIMBS,
    FieldCtx,
    from_limbs,
    from_mont,
    to_limbs,
    to_mont,
)
from .secp_batch import _add, _dbl, _is_zero_row, _select, _to_affine

CTX_Q = FieldCtx(BN254_FQ_MODULUS)  # BN254 base field (G1 coords)
SCALAR_BITS = 264  # full 22×12-bit limb coverage


def _seg_scan_add(ctx, pts, seg):
    """Segmented inclusive scan under the group law: pts is a Jacobian
    triple of (n, L) arrays sorted by segment key ``seg``; each output
    position holds the running sum of its segment's prefix.

    The log2(n) Hillis-Steele steps run as ONE ``fori_loop`` body with
    a dynamic shift (gather + validity mask) instead of a Python-
    unrolled chain of ``_add`` graphs: the unrolled form put ~log2(n)
    copies of the full group-law graph into ``_window_contrib`` and
    XLA compiles of the skeleton ran to many minutes — on the CPU
    fallback AND the chip. Identical math (a shifted-in zero row is
    the same infinity the old zero-concatenate produced)."""
    n = seg.shape[0]
    steps = max(1, (n - 1).bit_length())
    idx = jnp.arange(n)

    def body(i, cur):
        off = jnp.left_shift(jnp.int32(1), i)
        src = idx - off
        valid = src >= 0
        srcc = jnp.maximum(src, 0)
        shifted = tuple(
            jnp.where(valid[:, None], p[srcc], 0) for p in cur)
        seg_shift = jnp.where(valid, seg[srcc], -1)
        summed = _add(ctx, cur, shifted)
        return _select(seg == seg_shift, summed, cur)

    return lax.fori_loop(0, steps, body, pts)


@partial(jax.jit, static_argnames=("c",))
def _window_contrib(xs, ys, one, s_pl, w, c: int):
    """Bucket-weighted sum Σ d·S_d of one c-bit window (w traced — the
    64 windows share this compile). Returns a 1-lane Jacobian triple."""
    ctx = CTX_Q
    per = 12 // c
    limb = lax.dynamic_slice_in_dim(s_pl, w // per, 1, axis=1)[:, 0]
    d = ((limb >> (c * (w % per))) & ((1 << c) - 1)).astype(jnp.int32)

    order = jnp.argsort(d)              # fused sort+gather
    d_sorted = d[order]
    pts = (xs[order], ys[order], one)
    scan = _seg_scan_add(ctx, pts, d_sorted)

    nb = 1 << c
    is_tail = jnp.concatenate(
        [d_sorted[:-1] != d_sorted[1:], jnp.ones((1,), bool)])
    # one tail per present digit → unique rows; non-tails land on the
    # junk row nb and are never read
    idx = jnp.where(is_tail, d_sorted, nb)
    bucket = tuple(
        jnp.zeros((nb + 1, NUM_LIMBS), jnp.int32).at[idx].set(p)
        for p in scan)

    # Σ_{d>=1} d·S_d by suffix telescoping: run = Σ_{d>=j} S_d,
    # tot += run for j = nb-1 .. 1 (bucket 0 never enters). Rolled —
    # an unrolled 2·(nb−2) add chain of fori-looped mont_muls is
    # minutes of XLA compile (the fieldops.mont_pow lesson).
    run = tuple(p[nb - 1: nb] for p in bucket)

    def body(i, carry):
        run, tot = carry
        j = nb - 2 - i
        entry = tuple(
            lax.dynamic_slice_in_dim(p, j, 1, axis=0) for p in bucket)
        run = _add(ctx, run, entry)
        tot = _add(ctx, tot, run)
        return run, tot

    _, tot = lax.fori_loop(0, nb - 2, body, (run, run))
    return tot


@partial(jax.jit, static_argnames=("c",))
def _combine(acc, tot, c: int):
    for _ in range(c):
        acc = _dbl(CTX_Q, acc)
    return _add(CTX_Q, acc, tot)


def msm_device(points, scalars, c: int = 4, scalar_bits: int | None = None,
               affine: bool = True):
    """Σ scalars[i]·points[i] over BN254 G1 on the device.

    points: [(x, y)] affine int pairs (no identities); scalars: ints.
    Returns an affine (x, y) int pair, or None for the identity.

    ``scalar_bits`` bounds the window sweep when every scalar is known
    small (selector/0-1 columns — the host Pippenger skips empty
    windows the same way; raises if a scalar exceeds the bound).
    ``affine=False`` returns the raw Jacobian (x, y, z) ints instead of
    normalizing on device — the in-graph Fermat inversion is ~254
    sequential muls, which the tiny tier-1 CPU parity case (the r5
    kill's executable witness) verifies host-side instead."""
    if 12 % c:
        raise ValueError("window size must divide the 12-bit limb")
    nbits = SCALAR_BITS if scalar_bits is None else int(scalar_bits)
    if scalar_bits is not None:
        for s in scalars:
            if int(s) >> nbits:
                raise ValueError(
                    f"scalar exceeds the {nbits}-bit window bound")
    ctx = CTX_Q
    k = len(points)
    xs = to_mont(ctx, jnp.asarray(to_limbs([p[0] for p in points])))
    ys = to_mont(ctx, jnp.asarray(to_limbs([p[1] for p in points])))
    one = to_mont(ctx, jnp.asarray(to_limbs([1] * k)))
    s_pl = jnp.asarray(to_limbs([int(s) for s in scalars]))

    acc = (jnp.zeros((1, NUM_LIMBS), jnp.int32),) * 3  # ∞
    for w in range((nbits + c - 1) // c - 1, -1, -1):
        tot = _window_contrib(xs, ys, one, s_pl, w, c)
        acc = _combine(acc, tot, c)

    if not bool(np.asarray(~_is_zero_row(acc[2]))[0]):
        return None
    if not affine:
        return tuple(from_limbs(np.asarray(from_mont(ctx, a)))[0]
                     for a in acc)
    ax, ay = _to_affine(ctx, acc)
    x = from_limbs(np.asarray(from_mont(ctx, ax)))[0]
    y = from_limbs(np.asarray(from_mont(ctx, ay)))[0]
    return (x, y)
