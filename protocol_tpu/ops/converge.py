"""EigenTrust convergence kernels — the TPU side of the ConvergeBackend seam.

The reference's hot loop (``dynamic_sets/native.rs:319-329``) is a dense
O(I·N²) nested Python-style loop in the BN254 field; here the real-valued
twin runs as:

- **dense**: ``s ← s @ C`` under ``lax.fori_loop`` / ``lax.while_loop`` —
  an MXU matvec per iteration; right choice for fully-connected sets up to
  a few thousand peers.
- **sparse**: gather-SpMV over the degree-bucketed ELL transpose built by
  ``protocol_tpu.graph.build_operator`` — pure gathers + row reductions
  (VPU-friendly, no scatters), with the dangling-mass rank-1 correction
  applied implicitly.

Both come in fixed-iteration form (reference parity: exactly
NUM_ITERATIONS steps, ``circuits/mod.rs:41``) and adaptive form (converge
to an L1 tolerance — the deliberate semantic extension BASELINE.md's north
star asks for).

All functions are jit-compiled with static shapes; iteration counts are
static (unrolled loop bounds) or carried as while_loop state.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..graph import EllOperator
from ..utils import trace


class Semiring(NamedTuple):
    """The pluggable (add, mul) algebra of one converge sweep.

    The power iteration's inner product generalizes: a sweep computes
    ``new_s[i] = add_j mul(w_ji, s[j])`` over the SAME compiled
    operator layouts — only the combine/reduce ops change. Members are
    module-level jnp callables, so the tuple is hashable and rides
    through ``jax.jit`` as a static argument (one compile per algebra,
    never per value).

    - ``add``: binary combiner (the scatter/tail form of the reduce);
    - ``mul``: edge-weight application to a source score;
    - ``reduce``: the axis form of ``add`` (``jnp.sum`` / ``jnp.max``);
    - ``zero``: identity of ``add`` — the value every pad lane must
      yield. Both shipped semirings use 0.0, which is only an identity
      for ``max`` over NONNEGATIVE scores: every non-(+,×) semiring
      here assumes the trust invariant ``s >= 0`` (normalized weights,
      nonnegative starts preserve it).

    ``plusmul`` is classic EigenTrust; the DEFAULT converge entry
    points never dispatch through this seam at all (the pre-existing
    kernels run verbatim, same jit signatures). ``maxplus`` is
    bottleneck trust (max-min / widest-path, the tropical variant of
    arXiv 1906.05793): a peer's score is the best bottleneck over all
    trust paths reaching it, ``s[i] = max_j min(w_ji, s[j])`` — no
    dangling redistribution or damping (path semantics, not mass
    conservation); invalid slots are masked to 0.
    """

    name: str
    add: Callable
    mul: Callable
    reduce: Callable
    zero: float


PLUSMUL = Semiring("plusmul", jnp.add, jnp.multiply, jnp.sum, 0.0)
MAXPLUS = Semiring("maxplus", jnp.maximum, jnp.minimum, jnp.max, 0.0)

SEMIRINGS = {"plusmul": PLUSMUL, "maxplus": MAXPLUS}


def resolve_semiring(semiring) -> Semiring:
    """``None`` / name / ``Semiring`` → ``Semiring`` (default (+,×))."""
    if semiring is None:
        return PLUSMUL
    if isinstance(semiring, Semiring):
        return semiring
    try:
        return SEMIRINGS[semiring]
    except KeyError:
        raise ValueError(
            f"unknown semiring {semiring!r} (have: "
            f"{sorted(SEMIRINGS)})") from None


def semiring_tail(sr: Semiring, arrs: dict, s, base):
    """Post-reduce tail of one sweep under ``sr``: the (+,×) algebra
    keeps the dangling-mass rank-1 correction + damping
    (:func:`dangling_and_damping` — mass conservation); path algebras
    have no mass to conserve, so the tail is just the valid mask.
    ``sr`` is static under jit — this branch never appears in the
    compiled graph."""
    if sr.name == "plusmul":
        return dangling_and_damping(arrs, s, base)
    return base * arrs["valid"]


def record_converge_stats(backend: str, iters: int, delta, seconds: float,
                          n: int | None = None,
                          semiring: str = "plusmul") -> None:
    """Shared converge observability: every backend (gather, routed,
    sharded) reports its exit through this one seam so the instruments
    cannot diverge. Emits

    - ``ptpu_converge_iterations{backend,semiring}`` — the iteration
      count the power method actually ran: the convergence signal the
      EigenTrust analyses (arXiv:1603.00589, 2606.11956) say governs
      score quality, previously observable nowhere;
    - ``ptpu_converge_residual{backend,semiring}`` — the final
      relative-L1 delta (adaptive runs only; fixed-iteration runs pass
      ``delta=None``);
    - ``ptpu_converge_sweep_seconds{backend,semiring}`` — mean per-sweep
      (operator-apply) wall time, total/iters. The sweeps run inside a
      jitted ``while_loop``, so per-sweep timing cannot be observed
      in-loop without breaking compilation — the mean is the honest
      host-side view.
    """
    iters = int(iters)
    trace.gauge("converge_iterations").set(iters, backend=backend,
                                           semiring=semiring)
    if delta is not None:
        trace.gauge("converge_residual").set(float(delta), backend=backend,
                                             semiring=semiring)
    if iters > 0:
        trace.histogram("converge_sweep_seconds").observe(
            seconds / iters, backend=backend, semiring=semiring)
    trace.event("converge.done", backend=backend, iterations=iters,
                semiring=semiring, seconds=round(seconds, 6),
                **({} if n is None else {"n": n}),
                **({} if delta is None else {"residual": float(delta)}))


def record_refresh_scope(mode: str) -> None:
    """The one seam that says HOW a refresh swept the graph — the
    four-mode ladder ``partial → sampled → full → rebuild``:
    ``mode="partial"`` — host numpy sweeps restricted to the dirty
    frontier plus its fan-in (O(dirty), tiny frontiers);
    ``mode="device_partial"`` — the same frontier-restricted sweeps run
    through the device segment-gather kernel
    (:func:`partial_sweep_device`; frontiers past
    ``device_partial_threshold``);
    ``mode="sampled"`` — partially-observed sweeps over a bounded
    sample set (frontier + importance-sampled closure under
    ``sample_budget``) with the neglected-propagation mass tracked
    against the L1 honesty budget;
    ``mode="full"`` — whole-operator device sweeps on the patched
    operator (the partial bounds or the budget were exhausted);
    ``mode="rebuild"`` — served by a fresh operator build (the initial
    anchor, or a re-anchor after a capacity wall / lost delta log).
    Emits ``ptpu_refresh_sweep_scope_total{mode}`` so an operator can
    see the ratio drift (a rising full share means churn windows
    outgrow the sublinear bounds; a rising rebuild share means the
    delta engine is thrashing on re-anchors)."""
    trace.counter("refresh_sweep_scope").inc(mode=mode)
    trace.event("refresh.sweep_scope", mode=mode)


def timed_converge(backend: str, n: int, edges: int, signature, call,
                   fixed_iterations: int | None = None,
                   semiring: str = "plusmul"):
    """The one instrumentation wrapper every ConvergeBackend runs its
    converge through (span + compile watch + stats — a single seam so
    the two backends cannot drift): executes ``call`` under the
    ``converge.edges`` span and a ``compile_watch`` keyed on
    ``signature`` (the jit-cache identity — a second compile for the
    same signature is a steady-state recompile), and BLOCKS on the
    result before closing the timer: the converge functions are jitted
    and return at dispatch, so an unblocked wall time would record
    dispatch cost, not compute. The caller blocks immediately
    afterwards anyway (``np.asarray``), so this costs nothing.

    ``call`` returns device ``scores`` in fixed-iteration mode (pass
    ``fixed_iterations``) or ``(scores, iters, delta)`` in adaptive
    mode; returns ``call``'s result unchanged."""
    t0 = time.perf_counter()
    c0 = trace.thread_compile_seconds()
    with trace.span("converge.edges", backend=backend, n=n, edges=edges):
        with trace.compile_watch("converge", signature=signature):
            out = call()
            jax.block_until_ready(out)
    # carve the XLA compile out of the window (the listener runs on
    # this thread): a cold shape would otherwise inflate the per-sweep
    # mean by the whole compile, which ptpu_xla_compile_seconds
    # already measures on its own
    compile_dt = trace.thread_compile_seconds() - c0
    dt = max(time.perf_counter() - t0 - compile_dt, 0.0)
    if fixed_iterations is not None:
        record_converge_stats(backend, fixed_iterations, None, dt, n=n,
                              semiring=semiring)
    else:
        _, iters, delta = out
        # topic-batched calls return per-topic vectors; the recorded
        # count/residual are the worst topic (the honest scalar view)
        iters = np.max(np.asarray(iters))
        delta = np.max(np.asarray(delta))
        record_converge_stats(backend, int(iters), float(delta), dt, n=n,
                              semiring=semiring)
    return out


def warm_start_scores(prev, n: int, valid, initial_score: float):
    """Project a previous score vector onto a (possibly grown) peer set —
    the warm-start seam of the incremental refresh loop
    (``protocol_tpu.service``), per "Analysis of Power Iteration with
    Partially Observed Matrix-vector Products" (PAPERS.md): when only a
    small slice of the opinion matrix changed, the previous fixed point
    is a far better starting vector than uniform, and the adaptive loop
    stops in a handful of iterations instead of O(log(1/tol)/gap).

    ``prev`` covers the FIRST ``len(prev)`` slots of the new id space
    (service ids are append-only); new and previously-unseen peers start
    at ``initial_score``; invalid slots are zeroed. The result is
    rescaled so total mass equals the cold-start invariant
    ``n_valid * initial_score`` — power iteration under the
    mass-conserving trust operator converges to the fixed point with the
    mass of its starting vector, so without the rescale a warm and a
    cold converge would disagree by a scale factor whenever the peer
    set changed. Returns a float64 numpy vector (callers cast at device
    transfer).
    """
    valid = np.asarray(valid, dtype=bool)
    if valid.shape != (n,):
        raise ValueError(f"valid mask must have shape ({n},)")
    s = np.full(n, float(initial_score), dtype=np.float64)
    m = min(len(prev), n)
    carried = np.asarray(prev[:m], dtype=np.float64)
    if not len(carried) or float((carried * valid[:m]).sum()) <= 0.0:
        # degenerate carry-over (nothing, or all-zero/invalid): a
        # rescale would dump the whole mass on the new peers — cold
        # uniform is the only sensible start
        return valid.astype(np.float64) * float(initial_score)
    s[:m] = carried
    s *= valid
    target = float(valid.sum()) * float(initial_score)
    return s * (target / float(s.sum()))


@jax.jit
def partial_sweep_device(s, f_idx, f_valid, f_dang, f_ext,
                         e_row, e_src, e_w, scal):
    """One frontier-restricted power-iteration sweep on device — the
    segment-gather kernel behind ``incremental.device``.

    The full sweep applies the whole operator; this evaluates the
    update ONLY for a frontier row set, from its gathered in-edge
    segments: ``e_src[k]``/``e_w[k]`` is the k-th in-edge (source node,
    true normalized weight) of frontier row ``e_row[k]``, built
    host-side from the delta engine's CSR slices plus the per-row COO
    tail indexes. One gather + two segment-sums + elementwise tail —
    O(frontier fan-in) device work instead of O(E).

    The dangling-mass rank-1 shift stays the lazily-materialized
    SCALAR the host partial refresher tracks (``partial.py`` — change
    the math there and mirror it here; the device-vs-host parity test
    catches drift): ``scal`` packs the per-sweep host scalars
    ``[uni, uni_next, d_now, denom, keep, alpha, n_valid, total]`` as
    one device array so value changes never retrace.

    Shapes are the jit-cache identity — callers pow2-pad ``f_*`` and
    ``e_*`` (the delta patch-batch discipline) so the cache stays
    O(log frontier · log fan-in). Pad rows point at a dummy slot of
    ``s`` with ``f_valid = f_dang = 0`` and pad edges carry weight 0,
    so every pad lane computes exactly 0 and the frontier scatter
    stays deterministic (duplicate dummy indices all write 0).

    XLA:CPU constraint note (this box compiles limb-engine graphs for
    many minutes): this kernel is a fixed, loop-free graph — gathers,
    two segment scatter-adds and elementwise math — so its compile is
    cheap at every bucket shape. Keep it that way: no Python-unrolled
    per-sweep loops in here (roll any future iteration into a
    ``lax.fori_loop`` body), and never let a host float leak in as a
    traced constant (everything value-like rides in ``scal``).

    Returns ``(s2, changed, l1, d_delta, vsum, negl)``:
    ``s2`` — s with the frontier rows updated (store representation:
    true = s + uni·valid); ``changed`` — per-frontier-row true-value
    delta (the host expands the frontier where |changed| > drop_eps);
    ``l1`` — Σ|changed|; ``d_delta`` — dangling-mass delta of the
    store update; ``vsum`` — Σ valid over the frontier; ``negl`` —
    Σ|changed|·f_ext, the neglected-propagation mass bound of the
    sampled mode (``f_ext`` = per-row external out-weight; zeros in
    the plain partial mode).
    """
    uni = scal[0]
    uni_next = scal[1]
    d_now = scal[2]
    denom = scal[3]
    keep = scal[4]
    alpha = scal[5]
    n_valid = scal[6]
    total = scal[7]
    base = jnp.zeros(f_idx.shape[0], s.dtype).at[e_row].add(e_w * s[e_src])
    in_wsum = jnp.zeros(f_idx.shape[0], s.dtype).at[e_row].add(e_w)
    s_f = s[f_idx]
    base_true = base + uni * in_wsum
    s_true = s_f + uni * f_valid
    corr = (d_now - f_dang * s_true) / denom
    new_true = base_true + corr * f_valid
    # alpha == 0 => keep == 1 and the pretrust term vanishes: computing
    # the damped form unconditionally is exactly the undamped update
    new_true = keep * new_true + alpha * (
        f_valid / jnp.maximum(n_valid, 1.0)) * total
    changed = new_true - s_true
    new_store = new_true - uni_next * f_valid
    s2 = s.at[f_idx].set(new_store)
    l1 = jnp.sum(jnp.abs(changed))
    d_delta = jnp.sum(f_dang * (new_store - s_f))
    vsum = jnp.sum(f_valid)
    negl = jnp.sum(jnp.abs(changed) * f_ext)
    return s2, changed, l1, d_delta, vsum, negl


def operator_arrays(
    op: EllOperator, dtype=jnp.float32, alpha: float = 0.0, pretrust=None
) -> dict:
    """Device-ready pytree of an EllOperator's array leaves.

    ``alpha``/``pretrust`` enable the damped iteration
    s ← (1-α)·(Cᵀs + dangling-correction) + α·p. α=0 (default) is the
    reference's undamped semantics (native.rs:319-329); α>0 is the standard
    EigenTrust pre-trust mixing (BASELINE.json north star) which guarantees
    geometric convergence at rate (1-α) regardless of graph spectrum.
    ``pretrust`` defaults to uniform over valid peers, scaled so total mass
    is conserved for any s with sum(s) = sum(pretrust).
    """
    if pretrust is None:
        pretrust = op.valid.astype('float64') / max(op.n_valid, 1)
    return {
        "bucket_idx": tuple(jnp.asarray(b) for b in op.bucket_idx),
        "bucket_val": tuple(jnp.asarray(b, dtype=dtype) for b in op.bucket_val),
        "row_pos": jnp.asarray(op.row_pos),
        "valid": jnp.asarray(op.valid, dtype=dtype),
        "dangling": jnp.asarray(op.dangling, dtype=dtype),
        "n_valid": jnp.asarray(float(op.n_valid), dtype=dtype),
        "alpha": jnp.asarray(float(alpha), dtype=dtype),
        "pretrust": jnp.asarray(pretrust, dtype=dtype),
    }


def dangling_and_damping(arrs: dict, s: jnp.ndarray, base: jnp.ndarray
                         ) -> jnp.ndarray:
    """Shared tail of every SpMV backend: the dangling-mass rank-1
    correction plus damped pre-trust mixing.

    Dangling peers redistribute uniformly to every *other* valid peer
    (reference native.rs:263-278, as an implicit rank-1 update). α=0 is
    the pure reference semantics; for α>0, pretrust is scaled by the
    current total mass so the conservation invariant holds for any α.
    Both the gather path here and ops.routed share this function so the
    semantics cannot desynchronize. One twin CANNOT share it: the
    host-side partial refresher (``protocol_tpu/incremental/partial.py``)
    applies this same correction frontier-restricted, with ``d_mass``
    tracked incrementally across sweeps — change the math here and
    mirror it there (the residual-parity test catches drift).
    """
    d_mass = jnp.sum(s * arrs["dangling"])
    denom = jnp.maximum(arrs["n_valid"] - 1.0, 1.0)
    corr = (d_mass - arrs["dangling"] * s) / denom
    propagated = base + corr * arrs["valid"]

    alpha = arrs["alpha"]
    total = jnp.sum(s * arrs["valid"])
    return (1.0 - alpha) * propagated + alpha * arrs["pretrust"] * total


def spmv(arrs: dict, s: jnp.ndarray) -> jnp.ndarray:
    """One application of the normalized trust operator: returns Cᵀs with
    the dangling-mass correction.

    Per bucket: gather source scores, weight, reduce along the padded
    width. Bucket outputs concatenate (plus a zero slot for in-degree-0
    rows) and a permutation gather restores row order.
    """
    parts = [
        (val * s[idx]).sum(axis=-1)
        for idx, val in zip(arrs["bucket_idx"], arrs["bucket_val"])
    ]
    parts.append(jnp.zeros((1,), dtype=s.dtype))
    flat = jnp.concatenate(parts)
    base = flat[arrs["row_pos"]]
    return dangling_and_damping(arrs, s, base)


def adaptive_loop(step, s0: jnp.ndarray, tol: float, max_iterations: int,
                  accel_every: int = 0):
    """Shared adaptive-convergence driver: iterate ``step`` until the
    relative L1 delta ≤ tol (or max_iterations). Every backend (dense,
    gather-sparse, routed) runs this exact loop so tolerance semantics
    and iteration counts cannot diverge between them.

    ``accel_every > 0`` applies a safeguarded rank-1 minimal-polynomial
    extrapolation every that many iterations: with consecutive
    differences Δ1, Δ2, estimate the dominant contraction ratio
    r = ⟨Δ2,Δ1⟩/⟨Δ1,Δ1⟩ and jump s ← s + (r/(1−r))·Δ2 — the geometric
    series the dominant error mode would still contribute. The jump is
    an affine combination of mass-conserving iterates, so conservation
    is exact; r is clamped to [0, 0.9] so a misestimate cannot blow up,
    and the stopping delta is always the *unextrapolated* step
    contraction, so the tolerance semantics are unchanged.

    Returns (scores, iterations_run, final_relative_delta).
    """
    if accel_every == 1:
        # d1 would span the previous jump, corrupting the ratio estimate;
        # every >= 2 keeps both differences as clean power-iteration steps
        raise ValueError("accel_every must be 0 (off) or >= 2")
    norm = jnp.maximum(jnp.sum(jnp.abs(s0)), 1.0)

    def cond(state):
        _, _, i, delta = state
        return (delta > tol) & (i < max_iterations)

    def body(state):
        s_prev, s, i, _ = state
        s_next = step(s)
        delta = jnp.sum(jnp.abs(s_next - s)) / norm
        if accel_every:
            d1 = s - s_prev
            d2 = s_next - s
            den = jnp.sum(d1 * d1)
            r = jnp.sum(d2 * d1) / jnp.maximum(den, jnp.finfo(s.dtype).tiny)
            r = jnp.clip(r, 0.0, 0.9)
            # never jump on the stopping iteration — neither a tol stop
            # nor the max_iterations cap: the returned vector must be the
            # one the reported delta describes
            do_acc = (((i % accel_every) == accel_every - 1) & (i >= 1)
                      & (delta > tol) & (i + 1 < max_iterations))
            s_next = jnp.where(do_acc, s_next + (r / (1.0 - r)) * d2, s_next)
        return s, s_next, i + 1, delta

    _, s, iters, delta = lax.while_loop(
        cond, body,
        (s0, s0, jnp.int32(0), jnp.asarray(jnp.inf, s0.dtype)),
    )
    return s, iters, delta


@partial(jax.jit, static_argnames=("num_iterations",))
def converge_sparse_fixed(arrs: dict, s0: jnp.ndarray, num_iterations: int):
    """Reference-parity fixed-iteration power iteration on the sparse op."""
    return lax.fori_loop(0, num_iterations, lambda _, s: spmv(arrs, s), s0)


@partial(jax.jit, static_argnames=("max_iterations", "accel_every"))
def converge_sparse_adaptive(
    arrs: dict, s0: jnp.ndarray, tol: float = 1e-6, max_iterations: int = 100,
    accel_every: int = 0,
):
    """Iterate until the relative L1 delta ≤ tol (or max_iterations).

    Returns (scores, iterations_run, final_relative_delta).
    """
    return adaptive_loop(lambda s: spmv(arrs, s), s0, tol, max_iterations,
                         accel_every)


def spmv_semiring(arrs: dict, s: jnp.ndarray, sr: Semiring) -> jnp.ndarray:
    """One generalized sweep on the sparse (bucketed-ELL) operator:
    ``new_s[i] = add_j mul(w_ji, s[j])`` + the semiring tail. The SAME
    bucket layouts as :func:`spmv` — pad lanes carry ``idx=0, val=0``,
    so ``mul`` yields ``min(0, s[0]) = 0`` (nonnegative scores) or
    ``0·s[0] = 0``: exactly ``sr.zero``, and the reduce ignores them.
    The DEFAULT (+,×) entry points never route through here — this is
    the named-variant path only, so the existing jit signatures are
    untouched."""
    parts = [
        sr.reduce(sr.mul(val, s[idx]), axis=-1)
        for idx, val in zip(arrs["bucket_idx"], arrs["bucket_val"])
    ]
    parts.append(jnp.full((1,), sr.zero, dtype=s.dtype))
    flat = jnp.concatenate(parts)
    base = flat[arrs["row_pos"]]
    return semiring_tail(sr, arrs, s, base)


@partial(jax.jit, static_argnames=("sr", "num_iterations"))
def converge_sparse_fixed_semiring(arrs: dict, s0: jnp.ndarray,
                                   sr: Semiring, num_iterations: int):
    """Fixed-iteration twin of :func:`converge_sparse_fixed` under a
    pluggable semiring (static: one compile per algebra)."""
    return lax.fori_loop(0, num_iterations,
                         lambda _, s: spmv_semiring(arrs, s, sr), s0)


@partial(jax.jit, static_argnames=("sr", "max_iterations", "accel_every"))
def converge_sparse_adaptive_semiring(
    arrs: dict, s0: jnp.ndarray, sr: Semiring, tol: float = 1e-6,
    max_iterations: int = 100, accel_every: int = 0,
):
    """Adaptive twin of :func:`converge_sparse_adaptive` under a
    pluggable semiring — the same :func:`adaptive_loop` (max-min
    iteration is monotone per coordinate, so the L1 delta hits exactly
    0 at the fixed point and the tolerance stop is well-defined)."""
    return adaptive_loop(lambda s: spmv_semiring(arrs, s, sr), s0, tol,
                         max_iterations, accel_every)


@partial(jax.jit, static_argnames=("sr", "max_iterations"))
def converge_sparse_topics(arrs: dict, s0k: jnp.ndarray, sr: Semiring,
                           tol: float = 1e-6, max_iterations: int = 100):
    """Topic-batched adaptive converge: vmap K topic score-vectors
    ``s0k[K, n]`` through ONE sparse operator (TrustFlow-style
    topic-aware reputation, arXiv 2603.19452 — K contexts share the
    graph, differ in start/pre-trust vector). The while_loop batching
    rule select-masks per-topic updates, so each topic's trajectory is
    independent: a converged topic's vector stops changing while
    slower topics keep sweeping. Returns ``(scores[K, n], iters[K],
    delta[K])``; the operator (and its build cost) is paid once for
    all K."""
    return jax.vmap(
        lambda s0: adaptive_loop(lambda s: spmv_semiring(arrs, s, sr),
                                 s0, tol, max_iterations))(s0k)


@partial(jax.jit, static_argnames=("num_iterations",))
def converge_dense_fixed(c_norm: jnp.ndarray, s0: jnp.ndarray, num_iterations: int):
    """Dense fixed-iteration twin: s ← s @ C (row-stochastic C).

    ``s @ C`` computes new_s[i] = Σⱼ C[j,i]·s[j] — identical index
    convention to the reference loop (native.rs:322-326).
    """
    return lax.fori_loop(0, num_iterations, lambda _, s: s @ c_norm, s0)


@partial(jax.jit, static_argnames=("max_iterations",))
def converge_dense_adaptive(
    c_norm: jnp.ndarray, s0: jnp.ndarray, tol: float = 1e-6, max_iterations: int = 100
):
    return adaptive_loop(lambda s: s @ c_norm, s0, tol, max_iterations)
