"""Batched secp256k1 ECDSA on TPU — ingest-scale signature validation.

The reference validates every attestation with a scalar EC multiply
(``ecdsa/native.rs:382-395`` verify, ``:298-331`` recover — SURVEY.md
§3.1 marks pubkey recovery as the ingest hot spot: one EC scalar-mul
per attestation). This module runs N verifications/recoveries as one
device dispatch on the modulus-generic limb engine (``ops.fieldops``):
Jacobian point arithmetic over the secp256k1 base field and scalar
logic over the group order, batched along the lane axis.

Structure per signature: two fixed-base/variable-base scalar muls fused
in one 256-step Strauss ladder (per bit: one Jacobian double + one
table add from {∞, G, Q, G+Q}), with branchless infinity/equal-point
handling via lane selects. Bit-exact against ``crypto.secp256k1``.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.secp256k1 import GX, GY, N as SECP_N, P as SECP_P
from .fieldops import (
    NUM_LIMBS,
    FieldCtx,
    _cond_sub_p,
    add_mod,
    from_limbs,
    from_mont,
    inv_mod,
    mont_mul,
    sub_mod,
    to_limbs,
    to_mont,
)

CTX_P = FieldCtx(SECP_P)  # base field (curve coordinates)
CTX_N = FieldCtx(SECP_N)  # scalar field (signature algebra)

SCALAR_BITS = 256


def _const_mont(ctx: FieldCtx, value: int, n: int) -> jnp.ndarray:
    """Montgomery form of a host constant (value·R mod p), trace-safe."""
    row = to_limbs([value * ctx.r % ctx.modulus])[0]
    return jnp.broadcast_to(jnp.asarray(row, dtype=jnp.int32),
                            (n, NUM_LIMBS))


def _zeros(n: int) -> jnp.ndarray:
    return jnp.zeros((n, NUM_LIMBS), dtype=jnp.int32)


def _is_zero_row(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(x == 0, axis=1)


def _select(cond: jnp.ndarray, a, b):
    """Per-point select: cond (n,) picks coords from a else b."""
    c = cond[:, None]
    return tuple(jnp.where(c, ai, bi) for ai, bi in zip(a, b))


# --- Jacobian arithmetic (a = 0 curve) -------------------------------------

def _dbl(ctx, pt):
    """2P in Jacobian coordinates (valid for Z=0 → stays at infinity)."""
    x, y, z = pt
    a = mont_mul(ctx, x, x)
    b = mont_mul(ctx, y, y)
    c = mont_mul(ctx, b, b)
    xb = add_mod(ctx, x, b)
    d = sub_mod(ctx, sub_mod(ctx, mont_mul(ctx, xb, xb), a), c)
    d = add_mod(ctx, d, d)
    e = add_mod(ctx, add_mod(ctx, a, a), a)
    f = mont_mul(ctx, e, e)
    x3 = sub_mod(ctx, f, add_mod(ctx, d, d))
    c8 = add_mod(ctx, c, c)
    c8 = add_mod(ctx, c8, c8)
    c8 = add_mod(ctx, c8, c8)
    y3 = sub_mod(ctx, mont_mul(ctx, e, sub_mod(ctx, d, x3)), c8)
    yz = mont_mul(ctx, y, z)
    z3 = add_mod(ctx, yz, yz)
    return x3, y3, z3


def _add(ctx, p, q):
    """P + Q, branchless: handles ∞ operands, P == Q (falls back to the
    doubling formula) and P == −Q (→ ∞) via selects."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = mont_mul(ctx, z1, z1)
    z2z2 = mont_mul(ctx, z2, z2)
    u1 = mont_mul(ctx, x1, z2z2)
    u2 = mont_mul(ctx, x2, z1z1)
    s1 = mont_mul(ctx, y1, mont_mul(ctx, z2, z2z2))
    s2 = mont_mul(ctx, y2, mont_mul(ctx, z1, z1z1))
    h = sub_mod(ctx, u2, u1)
    rr = sub_mod(ctx, s2, s1)

    hh = mont_mul(ctx, h, h)
    hhh = mont_mul(ctx, h, hh)
    v = mont_mul(ctx, u1, hh)
    rr2 = mont_mul(ctx, rr, rr)
    x3 = sub_mod(ctx, sub_mod(ctx, rr2, hhh), add_mod(ctx, v, v))
    y3 = sub_mod(ctx, mont_mul(ctx, rr, sub_mod(ctx, v, x3)),
                 mont_mul(ctx, s1, hhh))
    z3 = mont_mul(ctx, mont_mul(ctx, z1, z2), h)
    general = (x3, y3, z3)

    p_inf = _is_zero_row(z1)
    q_inf = _is_zero_row(z2)
    h_zero = _is_zero_row(h)
    r_zero = _is_zero_row(rr)

    doubled = _dbl(ctx, p)
    inf = (_zeros(x1.shape[0]),) * 3

    out = _select(h_zero & r_zero, doubled, general)  # P == Q
    out = _select(h_zero & ~r_zero & ~p_inf & ~q_inf, inf, out)  # P == −Q
    out = _select(q_inf, p, out)
    out = _select(p_inf, q, out)
    return out


def _to_affine(ctx, pt):
    """Jacobian → affine Montgomery coords; ∞ → (0, 0)."""
    x, y, z = pt
    zi = inv_mod(ctx, z)  # Montgomery-domain inverse; 0 → 0
    zi2 = mont_mul(ctx, zi, zi)
    return mont_mul(ctx, x, zi2), mont_mul(ctx, y, mont_mul(ctx, zi, zi2))


def _bit(scalars: jnp.ndarray, j) -> jnp.ndarray:
    """Bit j of plain limb rows (traced j)."""
    from .fieldops import LIMB_BITS

    limb = lax.dynamic_slice_in_dim(scalars, j // LIMB_BITS, 1, axis=1)[:, 0]
    return (limb >> (j % LIMB_BITS)) & 1


@partial(jax.jit, static_argnames=())
def _strauss(u1_plain: jnp.ndarray, u2_plain: jnp.ndarray, q):
    """u1·G + u2·Q as one interleaved ladder. Scalars are plain limb
    rows; Q is an affine Montgomery pair. Returns a Jacobian point."""
    ctx = CTX_P
    n = u1_plain.shape[0]
    gx = _const_mont(ctx, GX, n)
    gy = _const_mont(ctx, GY, n)
    one = _const_mont(ctx, 1, n)
    g = (gx, gy, one)
    qx, qy = q
    qj = (qx, qy, one)
    gq = _add(ctx, g, qj)

    # table[i] for i = b1 + 2·b2: ∞, G, Q, G+Q — stacked (n, 4, L)
    inf = (_zeros(n),) * 3
    table = [jnp.stack([c0, c1, c2, c3], axis=1)
             for c0, c1, c2, c3 in zip(inf, g, qj, gq)]

    def body(i, acc):
        j = SCALAR_BITS - 1 - i
        acc = _dbl(ctx, acc)
        idx = _bit(u1_plain, j) + 2 * _bit(u2_plain, j)  # (n,)
        entry = tuple(
            jnp.take_along_axis(
                t, idx[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]
            for t in table
        )
        return _add(ctx, acc, entry)

    return lax.fori_loop(0, SCALAR_BITS, body, inf)


def _mod_n_plain(x_plain: jnp.ndarray) -> jnp.ndarray:
    """Reduce a base-field value (< p) into the scalar field: at most
    one subtract of n since p < 2n for secp256k1 (one conditional
    subtract — fieldops._cond_sub_p — is exact here)."""
    return _cond_sub_p(x_plain, CTX_N)


# --- public batch ops -------------------------------------------------------

def verify_batch(rs, ss, msgs, pub_points) -> np.ndarray:
    """Batched ECDSA verification, one ladder for the whole batch.

    Twin of ``crypto.secp256k1.EcdsaVerifier.verify`` (itself mirroring
    ``ecdsa/native.rs:382-395``): R' = (m·s⁻¹)·G + (r·s⁻¹)·Q, accept iff
    R' ≠ ∞ and R'.x mod n == r. Zero r/s and default (0, 0) pubkeys are
    rejected exactly like the scalar path.

    rs, ss, msgs: int lists; pub_points: [(x, y)] affine ints.
    Returns a bool numpy array.
    """
    n = len(rs)
    # r stays UNreduced for the final comparison: the scalar path
    # compares R'.x mod n against the raw signature r, so r >= n can
    # never verify (no malleability via r + n); only the u2 scalar uses
    # r mod n, exactly like the host's  u2 = sig.r * s_inv % N.
    r_raw = jnp.asarray(to_limbs(rs))
    s_pl = jnp.asarray(to_limbs([v % SECP_N for v in ss]))
    s_m = to_mont(CTX_N, s_pl)
    m_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in msgs])))
    r_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in rs])))

    s_inv = inv_mod(CTX_N, s_m)
    u1 = np.asarray(from_mont(CTX_N, mont_mul(CTX_N, m_m, s_inv)))
    u2 = np.asarray(from_mont(CTX_N, mont_mul(CTX_N, r_m, s_inv)))

    qx = to_mont(CTX_P, jnp.asarray(to_limbs([p[0] for p in pub_points])))
    qy = to_mont(CTX_P, jnp.asarray(to_limbs([p[1] for p in pub_points])))

    rpt = _strauss(jnp.asarray(u1), jnp.asarray(u2), (qx, qy))
    not_inf = ~_is_zero_row(rpt[2])
    ax, _ = _to_affine(CTX_P, rpt)
    x_plain = from_mont(CTX_P, ax)
    x_mod_n = _mod_n_plain(x_plain)
    x_matches = jnp.all(x_mod_n == r_raw, axis=1)

    nonzero = ~(_is_zero_row(r_raw) | _is_zero_row(s_pl))
    pk_ok = jnp.asarray(
        [not (p[0] == 0 and p[1] == 0) for p in pub_points])
    return np.asarray(not_inf & x_matches & nonzero & pk_ok)


def recover_batch(rs, ss, rec_ids, msgs):
    """Batched pubkey recovery: pk = r⁻¹·(s·R − m·G) with R lifted from
    (r, rec_id) — the ingest hot path (``ecdsa/native.rs:298-331``,
    driven per-attestation by ``Client.et_circuit_setup``).

    Returns (xs, ys, valid): affine coordinate int lists and a bool
    array (False where r does not lift to a curve point or the result
    is ∞)."""
    k = len(rs)
    r_pl = jnp.asarray(to_limbs([v % SECP_P for v in rs]))
    r_m = to_mont(CTX_P, r_pl)

    # lift_x: y = (x³ + 7)^((p+1)/4); valid iff y² == x³ + 7
    x3 = mont_mul(CTX_P, r_m, mont_mul(CTX_P, r_m, r_m))
    rhs = add_mod(CTX_P, x3, _const_mont(CTX_P, 7, k))
    from .fieldops import mont_pow

    y = mont_pow(CTX_P, rhs, (SECP_P + 1) // 4)
    lift_ok = jnp.all(mont_mul(CTX_P, y, y) == rhs, axis=1)

    # parity select: plain lsb vs rec_id
    y_plain = from_mont(CTX_P, y)
    # host recover_public_key lifts with bool(rec_id): ANY nonzero
    # rec_id selects the odd-y point (rec_id is a full wire byte)
    want_odd = jnp.asarray([int(bool(v)) for v in rec_ids], dtype=jnp.int32)
    y_odd = y_plain[:, 0] & 1
    y_neg = sub_mod(CTX_P, _zeros(k), y)
    y_sel = jnp.where((y_odd == want_odd)[:, None], y, y_neg)

    # scalars: u1 = −m·r⁻¹, u2 = s·r⁻¹ (mod n)
    rn_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in rs])))
    r_inv = inv_mod(CTX_N, rn_m)
    m_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in msgs])))
    s_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in ss])))
    u1 = sub_mod(CTX_N, jnp.zeros_like(m_m),
                 mont_mul(CTX_N, m_m, r_inv))
    u2 = mont_mul(CTX_N, s_m, r_inv)
    u1_pl = jnp.asarray(np.asarray(from_mont(CTX_N, u1)))
    u2_pl = jnp.asarray(np.asarray(from_mont(CTX_N, u2)))

    pk = _strauss(u1_pl, u2_pl, (r_m, y_sel))
    not_inf = ~_is_zero_row(pk[2])
    ax, ay = _to_affine(CTX_P, pk)
    xs = from_limbs(np.asarray(from_mont(CTX_P, ax)))
    ys = from_limbs(np.asarray(from_mont(CTX_P, ay)))
    return xs, ys, np.asarray(lift_ok & not_inf)
