"""Batched secp256k1 ECDSA on TPU — ingest-scale signature validation.

The reference validates every attestation with a scalar EC multiply
(``ecdsa/native.rs:382-395`` verify, ``:298-331`` recover — SURVEY.md
§3.1 marks pubkey recovery as the ingest hot spot: one EC scalar-mul
per attestation). This module runs N verifications/recoveries as one
device dispatch on the modulus-generic limb engine (``ops.fieldops``):
Jacobian point arithmetic over the secp256k1 base field and scalar
logic over the group order, batched along the lane axis.

Structure per signature: two fixed-base/variable-base scalar muls fused
in one 256-step Strauss ladder (per bit: one Jacobian double + one
table add from {∞, G, Q, G+Q}), with branchless infinity/equal-point
handling via lane selects. Bit-exact against ``crypto.secp256k1``.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.secp256k1 import (
    GLV_BETA,
    GX,
    GY,
    N as SECP_N,
    P as SECP_P,
    AffinePoint,
    glv_decompose,
)
from .fieldops import (
    NUM_LIMBS,
    FieldCtx,
    _cond_sub_p,
    add_mod,
    from_limbs,
    from_mont,
    inv_mod,
    mont_mul,
    sub_mod,
    to_limbs,
    to_mont,
)

CTX_P = FieldCtx(SECP_P)  # base field (curve coordinates)
CTX_N = FieldCtx(SECP_N)  # scalar field (signature algebra)

SCALAR_BITS = 256


def _const_mont(ctx: FieldCtx, value: int, n: int) -> jnp.ndarray:
    """Montgomery form of a host constant (value·R mod p), trace-safe."""
    row = to_limbs([value * ctx.r % ctx.modulus])[0]
    return jnp.broadcast_to(jnp.asarray(row, dtype=jnp.int32),
                            (n, NUM_LIMBS))


def _zeros(n: int) -> jnp.ndarray:
    return jnp.zeros((n, NUM_LIMBS), dtype=jnp.int32)


def _is_zero_row(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(x == 0, axis=1)


def _select(cond: jnp.ndarray, a, b):
    """Per-point select: cond (n,) picks coords from a else b."""
    c = cond[:, None]
    return tuple(jnp.where(c, ai, bi) for ai, bi in zip(a, b))


# --- Jacobian arithmetic (a = 0 curve) -------------------------------------

def _dbl(ctx, pt):
    """2P in Jacobian coordinates (valid for Z=0 → stays at infinity)."""
    x, y, z = pt
    a = mont_mul(ctx, x, x)
    b = mont_mul(ctx, y, y)
    c = mont_mul(ctx, b, b)
    xb = add_mod(ctx, x, b)
    d = sub_mod(ctx, sub_mod(ctx, mont_mul(ctx, xb, xb), a), c)
    d = add_mod(ctx, d, d)
    e = add_mod(ctx, add_mod(ctx, a, a), a)
    f = mont_mul(ctx, e, e)
    x3 = sub_mod(ctx, f, add_mod(ctx, d, d))
    c8 = add_mod(ctx, c, c)
    c8 = add_mod(ctx, c8, c8)
    c8 = add_mod(ctx, c8, c8)
    y3 = sub_mod(ctx, mont_mul(ctx, e, sub_mod(ctx, d, x3)), c8)
    yz = mont_mul(ctx, y, z)
    z3 = add_mod(ctx, yz, yz)
    return x3, y3, z3


def _add(ctx, p, q):
    """P + Q, branchless: handles ∞ operands, P == Q (falls back to the
    doubling formula) and P == −Q (→ ∞) via selects."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = mont_mul(ctx, z1, z1)
    z2z2 = mont_mul(ctx, z2, z2)
    u1 = mont_mul(ctx, x1, z2z2)
    u2 = mont_mul(ctx, x2, z1z1)
    s1 = mont_mul(ctx, y1, mont_mul(ctx, z2, z2z2))
    s2 = mont_mul(ctx, y2, mont_mul(ctx, z1, z1z1))
    h = sub_mod(ctx, u2, u1)
    rr = sub_mod(ctx, s2, s1)

    hh = mont_mul(ctx, h, h)
    hhh = mont_mul(ctx, h, hh)
    v = mont_mul(ctx, u1, hh)
    rr2 = mont_mul(ctx, rr, rr)
    x3 = sub_mod(ctx, sub_mod(ctx, rr2, hhh), add_mod(ctx, v, v))
    y3 = sub_mod(ctx, mont_mul(ctx, rr, sub_mod(ctx, v, x3)),
                 mont_mul(ctx, s1, hhh))
    z3 = mont_mul(ctx, mont_mul(ctx, z1, z2), h)
    general = (x3, y3, z3)

    p_inf = _is_zero_row(z1)
    q_inf = _is_zero_row(z2)
    h_zero = _is_zero_row(h)
    r_zero = _is_zero_row(rr)

    doubled = _dbl(ctx, p)
    inf = (_zeros(x1.shape[0]),) * 3

    out = _select(h_zero & r_zero, doubled, general)  # P == Q
    out = _select(h_zero & ~r_zero & ~p_inf & ~q_inf, inf, out)  # P == −Q
    out = _select(q_inf, p, out)
    out = _select(p_inf, q, out)
    return out


def _to_affine(ctx, pt):
    """Jacobian → affine Montgomery coords; ∞ → (0, 0)."""
    x, y, z = pt
    zi = inv_mod(ctx, z)  # Montgomery-domain inverse; 0 → 0
    zi2 = mont_mul(ctx, zi, zi)
    return mont_mul(ctx, x, zi2), mont_mul(ctx, y, mont_mul(ctx, zi, zi2))


def _bit(scalars: jnp.ndarray, j) -> jnp.ndarray:
    """Bit j of plain limb rows (traced j)."""
    from .fieldops import LIMB_BITS

    limb = lax.dynamic_slice_in_dim(scalars, j // LIMB_BITS, 1, axis=1)[:, 0]
    return (limb >> (j % LIMB_BITS)) & 1


@partial(jax.jit, static_argnames=())
def _strauss(u1_plain: jnp.ndarray, u2_plain: jnp.ndarray, q):
    """u1·G + u2·Q as one interleaved ladder. Scalars are plain limb
    rows; Q is an affine Montgomery pair. Returns a Jacobian point."""
    ctx = CTX_P
    n = u1_plain.shape[0]
    gx = _const_mont(ctx, GX, n)
    gy = _const_mont(ctx, GY, n)
    one = _const_mont(ctx, 1, n)
    g = (gx, gy, one)
    qx, qy = q
    qj = (qx, qy, one)
    gq = _add(ctx, g, qj)

    # table[i] for i = b1 + 2·b2: ∞, G, Q, G+Q — stacked (n, 4, L)
    inf = (_zeros(n),) * 3
    table = [jnp.stack([c0, c1, c2, c3], axis=1)
             for c0, c1, c2, c3 in zip(inf, g, qj, gq)]

    def body(i, acc):
        j = SCALAR_BITS - 1 - i
        acc = _dbl(ctx, acc)
        idx = _bit(u1_plain, j) + 2 * _bit(u2_plain, j)  # (n,)
        entry = tuple(
            jnp.take_along_axis(
                t, idx[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]
            for t in table
        )
        return _add(ctx, acc, entry)

    return lax.fori_loop(0, SCALAR_BITS, body, inf)


def _mod_n_plain(x_plain: jnp.ndarray) -> jnp.ndarray:
    """Reduce a base-field value (< p) into the scalar field: at most
    one subtract of n since p < 2n for secp256k1 (one conditional
    subtract — fieldops._cond_sub_p — is exact here)."""
    return _cond_sub_p(x_plain, CTX_N)


# --- GLV + fixed-base-window recovery ladder (round 5) ----------------------
#
# The 256-bit Strauss ladder above costs ~29 field muls per bit
# (double + branchless table add with its nested doubling fallback)
# ≈ 7.4k muls per lane — two thirds of measured ingest wall. Recovery
# Q = u1·G + u2·R is restructured the way the circuit path already is
# (zk/ecdsa_chip.py _glv_mul):
#
# - u1·G rides 64 unsigned 4-bit windows into PRECOMPUTED affine tables
#   T[j][d] = d·16^j·G — zero doublings, 64 mixed adds (~18 muls each);
# - u2·R splits through the λ-endomorphism (crypto glv_decompose,
#   host-side Babai: ~2.4 µs/lane) into 129-bit halves riding a joint
#   2-bit-window ladder over {i·(e1R) + j·(e2λR)} — 65 iterations of
#   2 doublings + 1 add, plus a 16-entry per-lane table (2 dbl, 11 add).
#
# ≈ 3.8k muls per lane, ~0.5× the one-ladder cost; bit-exact against
# the scalar oracle (tests/test_secp_batch.py).

FB_WINDOW_BITS = 4
FB_WINDOWS = 64  # 256 bits / 4
GLV_WINDOW_BITS = 2
GLV_WINDOWS = 65  # ceil(129 / 2) windows of the half-scalars


class _FixedBaseTables:
    """Affine Montgomery tables d·16^j·G, j<64, d<16 — built once on
    host (Python EC adds), closed over jitted ladders as constants
    ((64, 16, L) int32 ×2 ≈ 180 KB)."""

    def __init__(self):
        xs = np.zeros((FB_WINDOWS, 16, NUM_LIMBS), dtype=np.int32)
        ys = np.zeros((FB_WINDOWS, 16, NUM_LIMBS), dtype=np.int32)
        base = AffinePoint(GX, GY)
        for j in range(FB_WINDOWS):
            row = [AffinePoint.identity()]
            for _ in range(15):
                row.append(row[-1].add(base))
            mont = [(0, 0) if p.is_identity() else
                    (p.x * CTX_P.r % SECP_P, p.y * CTX_P.r % SECP_P)
                    for p in row]
            xs[j] = to_limbs([m[0] for m in mont])
            ys[j] = to_limbs([m[1] for m in mont])
            base = row[-1].add(base)  # 16^{j+1}·G
        # keep HOST arrays: the cache outlives traces, so storing a
        # jnp array materialized inside a jit trace would leak a tracer
        # into later traces (jnp.asarray at the use site is per-trace)
        self.xs = xs
        self.ys = ys


_FB_TABLES: list = []


def _fb_tables() -> _FixedBaseTables:
    if not _FB_TABLES:
        _FB_TABLES.append(_FixedBaseTables())
    return _FB_TABLES[0]


def _add_mixed(ctx, p, ex, ey, e_inf):
    """P (Jacobian) + E (affine Montgomery, Z=1), branchless: ∞
    operands, P == E (doubling fallback) and P == −E handled by lane
    selects; ``e_inf`` marks lanes whose table entry is the identity."""
    x1, y1, z1 = p
    z1z1 = mont_mul(ctx, z1, z1)
    u2 = mont_mul(ctx, ex, z1z1)
    s2 = mont_mul(ctx, ey, mont_mul(ctx, z1, z1z1))
    h = sub_mod(ctx, u2, x1)
    rr = sub_mod(ctx, s2, y1)
    hh = mont_mul(ctx, h, h)
    hhh = mont_mul(ctx, h, hh)
    v = mont_mul(ctx, x1, hh)
    rr2 = mont_mul(ctx, rr, rr)
    x3 = sub_mod(ctx, sub_mod(ctx, rr2, hhh), add_mod(ctx, v, v))
    y3 = sub_mod(ctx, mont_mul(ctx, rr, sub_mod(ctx, v, x3)),
                 mont_mul(ctx, y1, hhh))
    z3 = mont_mul(ctx, z1, h)
    general = (x3, y3, z3)

    n = x1.shape[0]
    p_inf = _is_zero_row(z1)
    h_zero = _is_zero_row(h)
    r_zero = _is_zero_row(rr)
    doubled = _dbl(ctx, p)
    inf = (_zeros(n),) * 3
    one = _const_mont(ctx, 1, n)
    lifted = (ex, ey, one)

    out = _select(h_zero & r_zero, doubled, general)  # P == E
    out = _select(h_zero & ~r_zero & ~p_inf, inf, out)  # P == −E
    out = _select(p_inf, lifted, out)
    out = _select(e_inf, p, out)  # E == ∞ (also wins when both ∞)
    return out


def _fb_digit(u_plain, j):
    """4-bit window j of (n, L) plain 12-bit limb rows; 12 = 3·4 so
    windows never straddle a limb."""
    from .fieldops import LIMB_BITS

    limb = lax.dynamic_slice_in_dim(u_plain, j // 3, 1, axis=1)[:, 0]
    return (limb >> (4 * (j % 3))) & 15


def _glv_digits(s_plain, w):
    """2-bit window w (traced) of a half-scalar's limb rows."""
    limb = lax.dynamic_slice_in_dim(s_plain, w // 6, 1, axis=1)[:, 0]
    return (limb >> (2 * (w % 6))) & 3


@partial(jax.jit, static_argnames=())
def _recover_glv(u1_plain, s1_plain, s2_plain, e1_neg, e2_neg, rx, ry):
    """u1·G + (e1·s1)·R + (e2·s2)·λR → affine Montgomery (x, y) and a
    not-∞ flag. Scalars are plain limb rows (s1, s2 < 2^129); rx/ry is
    the lifted R in affine Montgomery; e*_neg are bool lanes for the
    GLV component signs."""
    ctx = CTX_P
    n = u1_plain.shape[0]
    tab = _fb_tables()
    inf = (_zeros(n),) * 3
    one = _const_mont(ctx, 1, n)

    # --- fixed-base sum: 64 window adds, no doublings ------------------
    # (fori_loop, not unrolled: every mont_mul nests a while-loop, so an
    # unrolled 64×18-mul chain is minutes of XLA compile — the same
    # reason fieldops.mont_pow stays rolled)
    fbx = jnp.asarray(tab.xs)
    fby = jnp.asarray(tab.ys)

    def fb_body(j, acc):
        d = _fb_digit(u1_plain, j)
        ex = jnp.take(lax.dynamic_index_in_dim(fbx, j, keepdims=False),
                      d, axis=0)
        ey = jnp.take(lax.dynamic_index_in_dim(fby, j, keepdims=False),
                      d, axis=0)
        return _add_mixed(ctx, acc, ex, ey, d == 0)

    fb = lax.fori_loop(0, FB_WINDOWS, fb_body, inf)

    # --- GLV joint ladder over P1 = e1·R, P2 = e2·λR -------------------
    neg_ry = sub_mod(ctx, _zeros(n), ry)
    y1 = jnp.where(e1_neg[:, None], neg_ry, ry)
    y2 = jnp.where(e2_neg[:, None], neg_ry, ry)
    beta = _const_mont(ctx, GLV_BETA, n)
    x2 = mont_mul(ctx, rx, beta)
    p1 = (rx, y1, one)
    p2 = (x2, y2, one)

    # 16-entry joint table i·P1 + j·P2, (n, 16, L) per coord. The 13
    # point ops ride 3 BATCHED group ops on stacked lane blocks (the
    # compile-size discipline again, and fewer dispatch rounds):
    #   [2P1|2P2] = dbl([P1|P2]);  [3P1|3P2] = [2P1|2P2] + [P1|P2];
    #   the 9 interior entries = one 9n-lane add A[i] + B[j].
    p12 = tuple(jnp.concatenate([a, b]) for a, b in zip(p1, p2))
    d12 = _dbl(ctx, p12)
    t12 = _add(ctx, d12, p12)
    a_row = [inf, p1, tuple(c[:n] for c in d12), tuple(c[:n] for c in t12)]
    b_row = [inf, p2, tuple(c[n:] for c in d12), tuple(c[n:] for c in t12)]
    big_a = tuple(jnp.concatenate([a_row[ii][c] for jj in range(1, 4)
                                   for ii in range(1, 4)])
                  for c in range(3))
    big_b = tuple(jnp.concatenate([b_row[jj][c] for jj in range(1, 4)
                                   for ii in range(1, 4)])
                  for c in range(3))
    sums = _add(ctx, big_a, big_b)
    entries = []
    for jj in range(4):
        for ii in range(4):
            if jj == 0:
                entries.append(a_row[ii])
            elif ii == 0:
                entries.append(b_row[jj])
            else:
                k = (jj - 1) * 3 + (ii - 1)
                entries.append(tuple(
                    c[k * n:(k + 1) * n] for c in sums))
    table = [jnp.stack([e[c] for e in entries], axis=1)
             for c in range(3)]  # 3 × (n, 16, L)

    def body(i, acc):
        w = GLV_WINDOWS - 1 - i
        acc = _dbl(ctx, _dbl(ctx, acc))
        idx = _glv_digits(s1_plain, w) + 4 * _glv_digits(s2_plain, w)
        entry = tuple(
            jnp.take_along_axis(
                t, idx[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]
            for t in table
        )
        return _add(ctx, acc, entry)

    glv = lax.fori_loop(0, GLV_WINDOWS, body, inf)

    # --- combine + affine ---------------------------------------------
    pt = _add(ctx, glv, fb)
    not_inf = ~_is_zero_row(pt[2])
    ax, ay = _to_affine(ctx, pt)
    return (from_mont(ctx, ax), from_mont(ctx, ay), not_inf)


# --- public batch ops -------------------------------------------------------

def verify_batch(rs, ss, msgs, pub_points) -> np.ndarray:
    """Batched ECDSA verification, one ladder for the whole batch.

    Twin of ``crypto.secp256k1.EcdsaVerifier.verify`` (itself mirroring
    ``ecdsa/native.rs:382-395``): R' = (m·s⁻¹)·G + (r·s⁻¹)·Q, accept iff
    R' ≠ ∞ and R'.x mod n == r. Zero r/s and default (0, 0) pubkeys are
    rejected exactly like the scalar path.

    rs, ss, msgs: int lists; pub_points: [(x, y)] affine ints.
    Returns a bool numpy array.
    """
    n = len(rs)
    # r stays UNreduced for the final comparison: the scalar path
    # compares R'.x mod n against the raw signature r, so r >= n can
    # never verify (no malleability via r + n); only the u2 scalar uses
    # r mod n, exactly like the host's  u2 = sig.r * s_inv % N.
    r_raw = jnp.asarray(to_limbs(rs))
    s_pl = jnp.asarray(to_limbs([v % SECP_N for v in ss]))
    s_m = to_mont(CTX_N, s_pl)
    m_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in msgs])))
    r_m = to_mont(CTX_N, jnp.asarray(to_limbs([v % SECP_N for v in rs])))

    s_inv = inv_mod(CTX_N, s_m)
    u1 = np.asarray(from_mont(CTX_N, mont_mul(CTX_N, m_m, s_inv)))
    u2 = np.asarray(from_mont(CTX_N, mont_mul(CTX_N, r_m, s_inv)))

    qx = to_mont(CTX_P, jnp.asarray(to_limbs([p[0] for p in pub_points])))
    qy = to_mont(CTX_P, jnp.asarray(to_limbs([p[1] for p in pub_points])))

    rpt = _strauss(jnp.asarray(u1), jnp.asarray(u2), (qx, qy))
    not_inf = ~_is_zero_row(rpt[2])
    ax, _ = _to_affine(CTX_P, rpt)
    x_plain = from_mont(CTX_P, ax)
    x_mod_n = _mod_n_plain(x_plain)
    x_matches = jnp.all(x_mod_n == r_raw, axis=1)

    nonzero = ~(_is_zero_row(r_raw) | _is_zero_row(s_pl))
    pk_ok = jnp.asarray(
        [not (p[0] == 0 and p[1] == 0) for p in pub_points])
    return np.asarray(not_inf & x_matches & nonzero & pk_ok)


@partial(jax.jit, static_argnames=())
def _recover_prep(r_pl, rn_pl, m_pl, s_pl, want_odd):
    """Lift R from (r, parity) and derive the recovery scalars — the
    challenge-independent front half of recovery, one dispatch."""
    k = r_pl.shape[0]
    r_m = to_mont(CTX_P, r_pl)

    # lift_x: y = (x³ + 7)^((p+1)/4); valid iff y² == x³ + 7
    x3 = mont_mul(CTX_P, r_m, mont_mul(CTX_P, r_m, r_m))
    rhs = add_mod(CTX_P, x3, _const_mont(CTX_P, 7, k))
    from .fieldops import mont_pow

    y = mont_pow(CTX_P, rhs, (SECP_P + 1) // 4)
    lift_ok = jnp.all(mont_mul(CTX_P, y, y) == rhs, axis=1)

    # parity select: plain lsb vs rec_id (host recover_public_key lifts
    # with bool(rec_id): ANY nonzero rec_id selects the odd-y point)
    y_plain = from_mont(CTX_P, y)
    y_odd = y_plain[:, 0] & 1
    y_neg = sub_mod(CTX_P, _zeros(k), y)
    y_sel = jnp.where((y_odd == want_odd)[:, None], y, y_neg)

    # scalars: u1 = −m·r⁻¹, u2 = s·r⁻¹ (mod n)
    rn_m = to_mont(CTX_N, rn_pl)
    r_inv = inv_mod(CTX_N, rn_m)
    m_m = to_mont(CTX_N, m_pl)
    s_m = to_mont(CTX_N, s_pl)
    u1 = sub_mod(CTX_N, jnp.zeros_like(m_m),
                 mont_mul(CTX_N, m_m, r_inv))
    u2 = mont_mul(CTX_N, s_m, r_inv)
    return (r_m, y_sel, lift_ok,
            from_mont(CTX_N, u1), from_mont(CTX_N, u2))


def recover_submit(rs, ss, rec_ids, msgs, _prep=None):
    """Phase 1 of the split recovery: host limb prep + the
    challenge-independent ``_recover_prep`` dispatch (async — queues
    device work and returns). The split exists so a chunked caller can
    software-pipeline: while the device runs chunk i's ladder, the host
    builds chunk i+1's limbs here (``recover_stream``)."""
    k = len(rs)
    rs = [int(v) for v in rs]
    ss = [int(v) for v in ss]
    r_pl = jnp.asarray(to_limbs([v % SECP_P for v in rs]))
    rn_pl = jnp.asarray(to_limbs([v % SECP_N for v in rs]))
    m_pl = jnp.asarray(to_limbs([v % SECP_N for v in msgs]))
    s_pl = jnp.asarray(to_limbs([v % SECP_N for v in ss]))
    want_odd = jnp.asarray([int(bool(v)) for v in rec_ids],
                           dtype=jnp.int32)
    prep = (_prep or _recover_prep)(r_pl, rn_pl, m_pl, s_pl, want_odd)
    range_ok = np.array([0 < r < SECP_N and s % SECP_N != 0
                         for r, s in zip(rs, ss)], dtype=bool)
    return (k, prep, range_ok)


def recover_midstage(handle, _glv=None):
    """Phase 2: download u2 (syncs phase 1), host-side Babai GLV split
    (~2.4 µs/lane), then the ladder dispatch (async)."""
    k, (r_m, y_sel, lift_ok, u1, u2), range_ok = handle
    u2_ints = from_limbs(np.asarray(u2))
    e1_neg = np.zeros(k, dtype=bool)
    e2_neg = np.zeros(k, dtype=bool)
    halves1, halves2 = [], []
    for i, u in enumerate(u2_ints):
        h1, e1, h2, e2 = glv_decompose(u)
        halves1.append(h1)
        halves2.append(h2)
        e1_neg[i] = e1 < 0
        e2_neg[i] = e2 < 0
    s1l = to_limbs(halves1)
    s2l = to_limbs(halves2)
    ax, ay, not_inf = (_glv or _recover_glv)(
        u1, jnp.asarray(s1l), jnp.asarray(s2l),
        jnp.asarray(e1_neg), jnp.asarray(e2_neg), r_m, y_sel)
    return (ax, ay, lift_ok, not_inf, range_ok)


def recover_finalize(handle):
    """Phase 3: download the affine results (syncs the ladder) and
    assemble the validity mask."""
    ax, ay, lift_ok, not_inf, range_ok = handle
    xs = from_limbs(np.asarray(ax))
    ys = from_limbs(np.asarray(ay))
    return xs, ys, np.asarray(lift_ok & not_inf) & range_ok


def recover_stream(chunks, _prep=None, _glv=None):
    """Pipelined recovery over an iterable of (rs, ss, rec_ids, msgs)
    chunks, yielding (xs, ys, valid) per chunk in order.

    Two chunks are in flight: while the device runs chunk i's GLV
    ladder (the dominant span), the host builds chunk i+1's limbs and
    dispatches its prep — JAX dispatch is async through the tunnel, so
    the reorder alone buys the overlap. Results are bit-identical to
    per-chunk ``recover_batch`` (same kernels, same order within a
    chunk; pinned by tests/test_secp_batch.py::TestRecoverStream)."""
    mid = None
    for ch in chunks:
        sub = recover_submit(*ch, _prep=_prep)
        if mid is not None:
            yield recover_finalize(mid)
        mid = recover_midstage(sub, _glv=_glv)
    if mid is not None:
        yield recover_finalize(mid)


def recover_batch(rs, ss, rec_ids, msgs, _prep=None, _glv=None):
    """Batched pubkey recovery: pk = r⁻¹·(s·R − m·G) with R lifted from
    (r, rec_id) — the ingest hot path (``ecdsa/native.rs:298-331``,
    driven per-attestation by ``Client.et_circuit_setup``), on the
    GLV + fixed-base-window ladder (``_recover_glv``).

    Returns (xs, ys, valid): affine coordinate int lists and a bool
    array. A lane is valid iff r ∈ [1, n), s ≢ 0 (mod n), r lifts onto
    the curve and the result is not ∞ — EXACTLY the acceptance set of
    the scalar pipeline (recover, then verify with the recovered key):
    verify mod-reduces s, rejects r = 0 / r ≥ n through the final
    R'.x ≡ r comparison, and rejects the crafted sR = mG identity-key
    case via ``is_default``. Within that set recover⇒verify is an
    algebraic identity (R' = s⁻¹·(z·G + s·R − z·G) = R), so a True
    lane's key is GUARANTEED to verify — pinned lane-for-lane by
    tests/test_secp_batch.py::TestRecoverImpliesVerify.

    ``_prep``/``_glv`` override the two jitted device cores — the
    lane-sharded multichip twins (``parallel.ingest``) reuse this host
    orchestration unchanged (the ladders are embarrassingly lane-
    parallel; only the Babai split runs on host between them).

    Composition of recover_submit → recover_midstage → recover_finalize;
    chunked callers pipeline the phases via ``recover_stream``."""
    return recover_finalize(recover_midstage(
        recover_submit(rs, ss, rec_ids, msgs, _prep=_prep), _glv=_glv))
