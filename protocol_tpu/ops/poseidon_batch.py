"""Batched Poseidon hashing on TPU — the ingest-scale validation layer.

The reference hashes every attestation and opinion row with a scalar
width-5 Hades permutation (``poseidon/native/mod.rs:34-96``); at the
north-star scale (millions of signed attestations, SURVEY.md §7.2 step
5) hashing must be batched or ingestion becomes the bottleneck. This
module runs N permutations as one device dispatch on the int32
limb engine (``ops.fieldops``), bit-exact against the host
``crypto.poseidon`` implementation (same Grain-generated constants).

State layout: (n, WIDTH, L) Montgomery-domain limb rows. Round
constants and the MDS matrix are pre-converted to Montgomery form once
per (modulus, width) instance and closed over as jit constants.
"""

from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.poseidon import DEFAULT_WIDTH, poseidon_params
from ..utils.fields import Fr
from .fieldops import (
    NUM_LIMBS,
    FieldCtx,
    _lazy_rowsum_mod,
    _ripple,
    add_mod,
    from_limbs,
    from_mont,
    mont_mul,
    to_limbs,
    to_mont,
)


@lru_cache(maxsize=8)
def get_poseidon_batch(modulus: int = Fr.MODULUS,
                       width: int = DEFAULT_WIDTH) -> "PoseidonBatch":
    """Cached instance per (modulus, width): construction burns ~7 s of
    Montgomery constant conversion and ``permute_mont`` jit-caches on the
    instance, so callers must share one."""
    return PoseidonBatch(modulus, width)


class PoseidonBatch:
    """One Poseidon instance (modulus, width) with device constants.
    Prefer :func:`get_poseidon_batch` — a fresh instance recompiles."""

    def __init__(self, modulus: int = Fr.MODULUS, width: int = DEFAULT_WIDTH):
        self.ctx = FieldCtx(modulus)
        self.width = width
        rc, mds, full_rounds, partial_rounds = poseidon_params(width, modulus)
        self.full_rounds = full_rounds
        self.partial_rounds = partial_rounds
        to_m = lambda vals: (  # noqa: E731 - plain ints -> Montgomery rows
            np.asarray(to_mont(self.ctx, jnp.asarray(to_limbs(vals))))
        )
        total_rounds = full_rounds + partial_rounds
        self.rc_m = jnp.asarray(
            to_m(rc).reshape(total_rounds, width, NUM_LIMBS)
        )
        self.mds_m = jnp.asarray(
            to_m([mds[i][j] for i in range(width) for j in range(width)])
            .reshape(width, width, NUM_LIMBS)
        )

    # --- device core ------------------------------------------------------
    def _sbox(self, x: jnp.ndarray) -> jnp.ndarray:
        """x^5 rowwise: 3 Montgomery multiplies."""
        x2 = mont_mul(self.ctx, x, x)
        x4 = mont_mul(self.ctx, x2, x2)
        return mont_mul(self.ctx, x4, x)

    def _mds_apply(self, state: jnp.ndarray) -> jnp.ndarray:
        """out[b, i] = Σ_j mds[i, j] · state[b, j]."""
        n, w, L = state.shape
        a = jnp.broadcast_to(self.mds_m, (n, w, w, L)).reshape(-1, L)
        b = jnp.broadcast_to(state[:, None, :, :], (n, w, w, L)).reshape(-1, L)
        prod = mont_mul(self.ctx, a, b).reshape(n, w, w, L)
        acc = _ripple(
            jnp.sum(prod, axis=2, dtype=jnp.int32).reshape(n * w, L)
        )
        return _lazy_rowsum_mod(self.ctx, acc).reshape(n, w, L)

    def _round(self, state: jnp.ndarray, r, partial: bool) -> jnp.ndarray:
        n, w, L = state.shape
        rc = lax.dynamic_index_in_dim(self.rc_m, r, keepdims=False)  # (w, L)
        state = add_mod(
            self.ctx,
            state.reshape(n * w, L),
            jnp.tile(rc, (n, 1)),
        ).reshape(n, w, L)
        if partial:
            lane0 = self._sbox(state[:, 0, :])
            state = state.at[:, 0, :].set(lane0)
        else:
            state = self._sbox(state.reshape(n * w, L)).reshape(n, w, L)
        return self._mds_apply(state)

    @partial(jax.jit, static_argnames=("self",))
    def permute_mont(self, state: jnp.ndarray) -> jnp.ndarray:
        """Full Hades permutation on (n, width, L) Montgomery state."""
        half = self.full_rounds // 2

        def full_body(r, s):
            return self._round(s, r, partial=False)

        def partial_body(r, s):
            return self._round(s, r, partial=True)

        state = lax.fori_loop(0, half, full_body, state)
        state = lax.fori_loop(half, half + self.partial_rounds,
                              partial_body, state)
        state = lax.fori_loop(half + self.partial_rounds,
                              self.full_rounds + self.partial_rounds,
                              full_body, state)
        return state

    # --- host conveniences ------------------------------------------------
    def permute(self, states) -> list:
        """(n, width) plain ints → (n, width) plain ints, one permutation
        each; bit-exact twin of ``crypto.poseidon.Poseidon.permute``."""
        states = [[int(v) for v in row] for row in states]
        n = len(states)
        w = self.width
        flat = [v for row in states for v in row]
        st = to_mont(self.ctx, jnp.asarray(to_limbs(flat))).reshape(
            n, w, NUM_LIMBS)
        out = self.permute_mont(st)
        vals = from_limbs(
            np.asarray(from_mont(self.ctx, out.reshape(n * w, NUM_LIMBS))))
        return [vals[i * w:(i + 1) * w] for i in range(n)]

    def hash_batch(self, inputs) -> list:
        """Batch of ≤width-length input tuples → lane-0 digests; twin of
        ``Poseidon.hash`` (zero-padded single permutation). This is the
        ingest path: one call hashes every attestation in the batch."""
        w = self.width
        padded = [list(row) + [0] * (w - len(row)) for row in inputs]
        return [row[0] for row in self.permute(padded)]




# --- limb-plane engine variant (fieldops2) ---------------------------------

@lru_cache(maxsize=2)
def get_poseidon_batch_planes(width: int = DEFAULT_WIDTH
                              ) -> "PoseidonBatchPlanes":
    return PoseidonBatchPlanes(width)


class PoseidonBatchPlanes:
    """Hades permutation on the (L, n) limb-plane engine
    (``ops.fieldops2`` — the prover pipeline's arithmetic), Fr only.

    The row-engine ``PoseidonBatch`` above measures ~1 ms/hash on the
    chip (the (n, L) layout burns VPU lanes and its CIOS loops
    materialize state through HBM per limb step); this twin keeps the
    state as width contiguous (L, n) lane blocks and runs ~20x faster
    at ingest batch sizes — it is what ``client/ingest.py`` ships.
    Bit-exact against ``crypto.poseidon`` (tested).

    Partial rounds run in the OPTIMIZED sparse form (r5): with only
    lane 0 nonlinear, σ commutes with any matrix of shape
    diag(1, M̂) — σ(M'x + c) = M'σ(x + ĉ), ĉ = (c₀, M̂⁻¹c_tail) — so
    each round's dense MDS factors as M = M'·M'' with M'' sparse
    (dense first row/column, identity elsewhere: 2t−1 muls vs t²) and
    the accumulated dense parts collapse into ONE matrix applied after
    the segment. The factorization and transported constants are
    computed exactly over Fr at construction and SELF-CHECKED against
    the naive segment on random states before they are trusted
    (poseidon_params hands back the same Grain constants the scalar
    oracle uses, so the check pins end-to-end equality)."""

    def __init__(self, width: int = DEFAULT_WIDTH):
        from . import fieldops2 as f2

        self.f2 = f2
        self.width = width
        self.modulus = f2.P
        rc, mds, full_rounds, partial_rounds = poseidon_params(
            width, f2.P)
        self.full_rounds = full_rounds
        self.partial_rounds = partial_rounds
        R_ = f2.R_MONT
        P_ = f2.P

        def cplane(v):
            return f2.ints_to_planes([v * R_ % P_])

        total = full_rounds + partial_rounds
        self.rc_planes = jnp.asarray(np.stack([
            np.stack([cplane(rc[r * width + i]) for i in range(width)])
            for r in range(total)
        ]))  # (rounds, w, L, 1)
        self.mds_planes = jnp.asarray(np.stack([
            np.stack([cplane(mds[i][j]) for j in range(width)])
            for i in range(width)
        ]))  # (w, w, L, 1)

        # --- optimized partial-round preprocessing (exact Fr ints) ----
        t = width
        half = full_rounds // 2
        k = partial_rounds
        M = [[mds[i][j] % P_ for j in range(t)] for i in range(t)]
        seg_rc = [[rc[(half + r) * t + i] % P_ for i in range(t)]
                  for r in range(k)]

        def mat_mul(A, B):
            return [[sum(A[i][x] * B[x][j] for x in range(t)) % P_
                     for j in range(t)] for i in range(t)]

        def mat_inv(A):
            n_ = len(A)
            aug = [[A[i][j] % P_ for j in range(n_)]
                   + [1 if i == j else 0 for j in range(n_)]
                   for i in range(n_)]
            for col in range(n_):
                piv = next(r for r in range(col, n_) if aug[r][col])
                aug[col], aug[piv] = aug[piv], aug[col]
                inv = pow(aug[col][col], -1, P_)
                aug[col] = [v * inv % P_ for v in aug[col]]
                for r in range(n_):
                    if r != col and aug[r][col]:
                        f_ = aug[r][col]
                        aug[r] = [(aug[r][j] - f_ * aug[col][j]) % P_
                                  for j in range(2 * n_)]
            return [row[n_:] for row in aug]

        # recurrence: M_0 = M; factor M_{j-1} = M'·M'' and absorb M'
        # into M_j = M·M'. Round j's constant transports through
        # M̂_{j-1}⁻¹ on the tail lanes.
        sparse = []   # per j=1..k-1: (M00, v[t-1], w_hat[t-1])
        chat = []     # per j=1..k-1: transported constant t-vector
        Mj = [row[:] for row in M]
        for j in range(1, k):
            Mhat = [[Mj[i][x] for x in range(1, t)] for i in range(1, t)]
            Mhat_inv = mat_inv(Mhat)
            w = [Mj[i][0] for i in range(1, t)]
            w_hat = [sum(Mhat_inv[i][x] * w[x] for x in range(t - 1))
                     % P_ for i in range(t - 1)]
            sparse.append((Mj[0][0], [Mj[0][x] for x in range(1, t)],
                           w_hat))
            c = seg_rc[j]
            c_tail = [sum(Mhat_inv[i][x] * c[1 + x]
                          for x in range(t - 1)) % P_
                      for i in range(t - 1)]
            chat.append([c[0]] + c_tail)
            Mprime = [[1 if (i == 0 and x == 0) else 0
                       for x in range(t)] for i in range(t)]
            for i in range(1, t):
                for x in range(1, t):
                    Mprime[i][x] = Mhat[i - 1][x - 1]
            Mj = mat_mul(M, Mprime)
        M_last = Mj
        # the factorizations were built back-to-front of the APPLY
        # order: sparse[0]/chat[0] correspond to the matrix between
        # σ_0 and σ_1... self-check decides if the ordering is right.

        def sbox0_int(s):
            return [pow(s[0], 5, P_)] + s[1:]

        def naive_segment(s):
            for r in range(k):
                s = [(s[i] + seg_rc[r][i]) % P_ for i in range(t)]
                s = sbox0_int(s)
                s = [sum(M[i][j] * s[j] for j in range(t)) % P_
                     for i in range(t)]
            return s

        def opt_segment(s):
            y = [(s[i] + seg_rc[0][i]) % P_ for i in range(t)]
            y = sbox0_int(y)
            for j in range(1, k):
                M00, v, w_hat = sparse[j - 1]
                y0 = (M00 * y[0]
                      + sum(v[x] * y[1 + x] for x in range(t - 1))) % P_
                tail = [(w_hat[i] * y[0] + y[1 + i]) % P_
                        for i in range(t - 1)]
                y = [y0] + tail
                y = [(y[i] + chat[j - 1][i]) % P_ for i in range(t)]
                y = sbox0_int(y)
            return [sum(M_last[i][j] * y[j] for j in range(t)) % P_
                    for i in range(t)]

        import random as _random

        _rng = _random.Random(0x9051D07)
        for _ in range(3):
            probe = [_rng.randrange(P_) for _ in range(t)]
            if naive_segment(probe) != opt_segment(probe):
                raise AssertionError(
                    "optimized Poseidon partial-segment preprocessing "
                    "diverged from the naive segment — refusing to "
                    "ship wrong hashes")

        # device-side lazy-accumulation envelope: tail lanes grow by a
        # < 3p unreduced increment per sparse round (mm product < 2p +
        # a Montgomery constant < p) and only reduce at mlast_apply, so
        # the value entering a CIOS multiply reaches ~(11 + 3(k−1))·p.
        # CIOS is exact for inputs < 2^262-ish (fieldops2 contract);
        # the constructor's exact-int self-check CANNOT see a
        # device-side overflow, so fail loudly for round counts the
        # envelope does not cover instead of hashing wrongly.
        if (11 + 3 * (k - 1)) * P_ >= 1 << 262:
            raise AssertionError(
                f"partial_rounds={k} exceeds the sparse segment's lazy "
                "accumulation envelope — add periodic reductions "
                "before using this configuration")

        # device constants for the optimized segment
        self.seg_c0 = jnp.asarray(np.stack(
            [cplane(seg_rc[0][i]) for i in range(t)]))  # (w, L, 1)
        self.seg_m00 = jnp.asarray(np.stack(
            [cplane(sparse[j][0]) for j in range(k - 1)]))  # (k-1, L, 1)
        self.seg_v = jnp.asarray(np.stack(
            [np.stack([cplane(sparse[j][1][x]) for x in range(t - 1)])
             for j in range(k - 1)]))  # (k-1, t-1, L, 1)
        self.seg_what = jnp.asarray(np.stack(
            [np.stack([cplane(sparse[j][2][x]) for x in range(t - 1)])
             for j in range(k - 1)]))  # (k-1, t-1, L, 1)
        # chat is ADDED to the Montgomery-domain state, so it carries
        # the same R factor as every other constant here
        self.seg_chat = jnp.asarray(np.stack(
            [np.stack([cplane(chat[j][i]) for i in range(t)])
             for j in range(k - 1)]))  # (k-1, w, L, 1)
        self.seg_mlast = jnp.asarray(np.stack(
            [np.stack([cplane(M_last[i][j]) for j in range(t)])
             for i in range(t)]))  # (w, w, L, 1)

    @partial(jax.jit, static_argnames=("self",))
    def permute_mont(self, state: jnp.ndarray) -> jnp.ndarray:
        """(L, w·n) Montgomery planes (lane blocks) → same, permuted."""
        f2 = self.f2
        w = self.width
        L = f2.L
        n = state.shape[1] // w
        half = self.full_rounds // 2
        mm = f2.mont_mul_compact

        def lane(s, i):
            return lax.dynamic_slice_in_dim(s, i * n, n, axis=1)

        def sbox(x):
            x2 = mm(x, x)
            return mm(mm(x2, x2), x)

        def add_vec(s, vec):  # vec: (w, L, 1) Montgomery constants
            tiled = jnp.concatenate(
                [jnp.broadcast_to(vec[i], (L, n)) for i in range(w)],
                axis=1)
            return f2.ripple(s + tiled, passes=1)

        def add_rc(s, r):
            return add_vec(s, lax.dynamic_index_in_dim(
                self.rc_planes, r, keepdims=False))

        def mat_apply(s, planes):  # planes: (w, w, L, 1)
            outs = []
            for i in range(w):
                acc = None
                for j in range(w):
                    term = mm(lane(s, j), jnp.broadcast_to(
                        planes[i, j], (L, n)))
                    acc = term if acc is None else f2.ripple(acc + term, 1)
                outs.append(acc)
            return jnp.concatenate(outs, axis=1)

        def full_round(r, s):
            s = add_rc(s, r)
            return mat_apply(sbox(s), self.mds_planes)

        # --- optimized partial segment (see __init__): per round one
        # lane-0 sbox + a SPARSE matrix (2t−1 muls, vs the dense t²),
        # with the accumulated dense parts collapsed into seg_mlast

        def partial_sparse(j, s):
            # j indexes seg arrays (round j+1 of the segment)
            y0 = lane(s, 0)
            m00 = jnp.broadcast_to(
                lax.dynamic_index_in_dim(self.seg_m00, j,
                                         keepdims=False), (L, n))
            acc = mm(y0, m00)
            v = lax.dynamic_index_in_dim(self.seg_v, j, keepdims=False)
            what = lax.dynamic_index_in_dim(self.seg_what, j,
                                            keepdims=False)
            tails = []
            for i in range(w - 1):
                yi = lane(s, 1 + i)
                acc = f2.ripple(
                    acc + mm(yi, jnp.broadcast_to(v[i], (L, n))), 1)
                tails.append(f2.ripple(
                    yi + mm(y0, jnp.broadcast_to(what[i], (L, n))), 1))
            out = jnp.concatenate([acc] + tails, axis=1)
            out = add_vec(out, lax.dynamic_index_in_dim(
                self.seg_chat, j, keepdims=False))
            s0 = sbox(lane(out, 0))
            return lax.dynamic_update_slice_in_dim(out, s0, 0, axis=1)

        state = lax.fori_loop(0, half, full_round, state)
        state = add_vec(state, self.seg_c0)
        s0 = sbox(lane(state, 0))
        state = lax.dynamic_update_slice_in_dim(state, s0, 0, axis=1)
        state = lax.fori_loop(0, self.partial_rounds - 1,
                              partial_sparse, state)
        state = mat_apply(state, self.seg_mlast)
        state = lax.fori_loop(half + self.partial_rounds,
                              self.full_rounds + self.partial_rounds,
                              full_round, state)
        return state

    def hash_submit(self, inputs) -> tuple:
        """Dispatch half of ``hash_batch``: host block build + the
        permutation dispatch (async). Returns an opaque handle for
        ``hash_finalize`` — the split lets a chunked ingest pipeline
        hash chunk i+1 while the recovery ladder runs chunk i."""
        f2 = self.f2
        w = self.width
        n = len(inputs)
        P_, R_ = f2.P, f2.R_MONT
        # lane-major blocks, Montgomery form on host (one python mul
        # per value; values are small ints for attestation rows)
        blocks = np.zeros((n * w, 4), dtype="<u8")
        flat_idx = 0
        for i in range(w):
            for row in inputs:
                v = int(row[i]) if i < len(row) else 0
                blocks[flat_idx] = np.frombuffer(
                    (v % P_ * R_ % P_).to_bytes(32, "little"), dtype="<u8")
                flat_idx += 1
        planes = jnp.asarray(f2.pack_u64(blocks).astype(np.int32))
        out = self.permute_mont(planes)
        digest = lax.dynamic_slice_in_dim(out, 0, n, axis=1)
        ready = f2._pack16_slices(f2.canonical(
            jax.jit(f2.exit_mont)(digest)))
        return (ready, n)

    @staticmethod
    def hash_finalize(handle) -> list:
        """Download half of ``hash_batch``: syncs the permutation and
        converts the packed digests to host ints."""
        ready, n = handle
        host = np.ascontiguousarray(np.asarray(ready).T).view("<u8")
        return [int.from_bytes(host[i].tobytes(), "little")
                for i in range(n)]

    def hash_batch(self, inputs) -> list:
        """Batch of ≤width tuples → lane-0 digests (ints); the ingest
        hot path. Host↔device conversion rides fieldops2's vectorized
        u64 pack (the (n, L) engine's per-int python loops were ~2 s
        per 32k batch on their own). Composition of hash_submit →
        hash_finalize; chunked callers pipeline the halves."""
        return self.hash_finalize(self.hash_submit(inputs))
