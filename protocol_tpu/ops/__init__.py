"""TPU compute kernels: converge (dense + bucketed-ELL SpMV), and batched
crypto/field primitives."""

from .converge import (
    converge_dense_fixed,
    converge_dense_adaptive,
    converge_sparse_fixed,
    converge_sparse_adaptive,
    operator_arrays,
    spmv,
)

__all__ = [
    "converge_dense_fixed",
    "converge_dense_adaptive",
    "converge_sparse_fixed",
    "converge_sparse_adaptive",
    "operator_arrays",
    "spmv",
]
