"""TPU compute kernels: converge (dense + bucketed-ELL SpMV + Clos-routed
SpMV), static-permutation routing, batched big-prime field arithmetic,
and batched Poseidon hashing."""

from .clos import RoutePlan, apply_route, plan_route, route_bits
from .converge import (
    converge_dense_fixed,
    converge_dense_adaptive,
    converge_sparse_fixed,
    converge_sparse_adaptive,
    operator_arrays,
    spmv,
)
from .routed import (
    RoutedOperator,
    build_routed_operator,
    converge_routed_adaptive,
    converge_routed_fixed,
    routed_arrays,
    spmv_routed,
)
from .fieldops import (
    FieldCtx,
    add_mod,
    field_converge,
    from_limbs,
    from_mont,
    inv_mod,
    mont_matvec,
    mont_mul,
    mont_pow,
    sub_mod,
    to_limbs,
    to_mont,
)
from .poseidon_batch import PoseidonBatch
from .secp_batch import recover_batch, verify_batch

__all__ = [
    "RoutePlan",
    "apply_route",
    "plan_route",
    "route_bits",
    "RoutedOperator",
    "build_routed_operator",
    "converge_routed_adaptive",
    "converge_routed_fixed",
    "routed_arrays",
    "spmv_routed",
    "converge_dense_fixed",
    "converge_dense_adaptive",
    "converge_sparse_fixed",
    "converge_sparse_adaptive",
    "operator_arrays",
    "spmv",
    "FieldCtx",
    "add_mod",
    "field_converge",
    "from_limbs",
    "from_mont",
    "inv_mod",
    "mont_matvec",
    "mont_mul",
    "mont_pow",
    "sub_mod",
    "to_limbs",
    "to_mont",
    "PoseidonBatch",
    "recover_batch",
    "verify_batch",
]
