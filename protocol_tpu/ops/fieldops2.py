"""Limb-plane BN254 field engine — the TPU prover pipeline's arithmetic.

A second-generation device field engine next to ``fieldops.py``, built
for the prover's polynomial pipeline (``ops/ntt_tpu.py``,
``zk/prover_tpu.py``) where arrays are millions of elements:

- **Layout**: n elements are stored as ``(L, n)`` int32 — L=22 little-
  endian 12-bit limbs on the *sublane* axis. XLA pads the minor two dims
  to (8, 128) tiles, so the fieldops.py ``(n, L)`` layout burns 5.8× HBM
  and VPU lanes (22 → 128); limb-plane pads only 22 → 24.
- **Montgomery domain throughout**: device arrays hold x̃ = x·R mod p
  (R = 2^264). ``mont_mul(x̃, ỹ) = (xy)~`` closes over the domain; host
  conversion happens in numpy at the wire boundary (`pack`/`unpack`).
- **Relaxed form**: limbs < 2^13, value < 2p. ``mont_mul`` accepts and
  produces relaxed rows (CIOS with a 2-pass carry ripple, no trailing
  conditional subtract) — exactness is by-value mod p, tested against
  Python ints.
- **MXU interface**: ``to_mxu_planes``/``reduce_mxu_planes`` convert to
  and from 6-bit int8 planes for exact f32/int8 systolic matmuls (a
  6-bit × 6-bit product summed over ≤ 2^12 terms stays below 2^24 —
  exact in f32 — and below 2^31 across ≤ 44 plane-combines in int32).

Reference anchor: this replaces the scalar Rust field arithmetic the
reference's halo2 prover runs on the CPU (``utils.rs:206-228``); the
layout choices are TPU-tiling-driven, not a translation.
"""

from __future__ import annotations

import os
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.fields import BN254_FR_MODULUS

B = 12
L = 22
MASK = (1 << B) - 1
B6 = 6
L6 = 2 * L  # 44 six-bit planes
MASK6 = (1 << B6) - 1

P = BN254_FR_MODULUS
# Montgomery radix: L+1 reduction steps (one more than the limb count),
# so CIOS output values land below p + 2^237 — the top limb plane is
# then provably tiny and the partial carry ripple cannot lose a carry
# past plane L−1 (see mont_mul).
R_EXP = B * (L + 1)                  # 276
R_MONT = pow(2, R_EXP, P)            # R mod p
R2_MONT = R_MONT * R_MONT % P        # R^2 mod p
P_INV_NEG = (-pow(P, -1, 1 << B)) % (1 << B)

_P_LIMBS = tuple((P >> (B * i)) & MASK for i in range(L))


def _const_planes(v: int, n: int | None = None) -> jnp.ndarray:
    """(L, 1) or (L, n) int32 limb planes of a Python int (< 2^264)."""
    limbs = [(v >> (B * i)) & MASK for i in range(L)]
    arr = jnp.asarray(limbs, dtype=jnp.int32).reshape(L, 1)
    if n is not None:
        arr = jnp.broadcast_to(arr, (L, n))
    return arr


P_PLANES = None  # initialized lazily inside jit via _const_planes(P)


# --- host <-> device packing (numpy, vectorized) ---------------------------

def pack_u64(arr_u64: np.ndarray, to_mont: bool = False) -> np.ndarray:
    """(n, 4) little-endian u64 standard-form array → (L, n) int32 limb
    planes. ``to_mont`` is handled on device (`enter_mont`), not here."""
    n = arr_u64.shape[0]
    a = np.ascontiguousarray(arr_u64).view(np.uint64).reshape(n, 4)
    out = np.empty((L, n), dtype=np.int32)
    # limb i covers bits [12i, 12i+12): source word + shift
    for i in range(L):
        bit = B * i
        w, off = bit // 64, bit % 64
        lo = a[:, w] >> np.uint64(off)
        if off > 52 and w + 1 < 4:
            lo = lo | (a[:, w + 1] << np.uint64(64 - off))
        out[i] = (lo & np.uint64(MASK)).astype(np.int32)
    return out


def unpack_u64(planes: np.ndarray) -> np.ndarray:
    """(L, n) canonical int32 planes → (n, 4) little-endian u64 array."""
    planes = np.asarray(planes)
    n = planes.shape[1]
    out = np.zeros((n, 4), dtype=np.uint64)
    for i in range(L):
        bit = B * i
        w, off = bit // 64, bit % 64
        v = planes[i].astype(np.uint64)
        out[:, w] |= (v << np.uint64(off)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        if off > 52 and w + 1 < 4:
            out[:, w + 1] |= v >> np.uint64(64 - off)
    return out.view("<u8")


# --- carries ----------------------------------------------------------------

def ripple(t: jnp.ndarray, passes: int = 2) -> jnp.ndarray:
    """Partial carry propagation on (K, n) planes: each pass divides the
    excess by 2^B. Two passes take CIOS output (< 2^18 per limb) to
    relaxed (< 2^13). The TOP plane is never masked — it accumulates
    incoming carries instead of silently dropping its own carry-out, so
    the represented value is always preserved exactly (values within
    ~2^13·2^{B(K−1)} of the top stay representable)."""
    for _ in range(passes):
        carry = t[:-1] >> B
        low = t[:-1] & MASK
        t = jnp.concatenate([low, t[-1:]], axis=0) + jnp.concatenate(
            [jnp.zeros((1,) + t.shape[1:], jnp.int32), carry], axis=0)
    return t


def _lookahead_chain(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Kogge-Stone carry/borrow lookahead over the limb axis: given
    per-limb generate/propagate flags (MUST be 0/1 int32 — the bitwise
    combine is wrong for values ≥ 2), returns the carry INTO each limb
    (combined carry-out of all limbs below it) in log₂(L) steps."""

    def combine(lo, hi):
        g_lo, p_lo = lo
        g_hi, p_hi = hi
        return g_hi | (p_hi & g_lo), p_hi & p_lo

    G, _ = lax.associative_scan(combine, (g, p), axis=0)
    return jnp.concatenate(
        [jnp.zeros((1,) + g.shape[1:], jnp.int32), G[:-1]], axis=0)


def _assert_relaxed(m) -> None:
    """PTPU_DEBUG_BOUNDS=1 guard: canon_limbs' lookahead is exact only
    for relaxed limbs (< 2^13); fail loudly at the violating call."""
    if int(m) >= (1 << 13):
        raise AssertionError(
            f"canon_limbs input limb {int(m)} ≥ 2^13 — outside the "
            "single-ripple + unit-carry lookahead exactness bound")


def canon_limbs(x: jnp.ndarray) -> jnp.ndarray:
    """Full carry propagation to limbs < 2^B below the top plane (value
    untouched — the TOP limb stays unmasked and absorbs every incoming
    carry, exactly like ``ripple``) — exact for ANY relaxed input
    (limbs < 2^13), including adversarial all-0xFFF runs that a fixed
    ripple-pass count would mis-canonicalize: one ripple pass bounds
    every limb by 2^B, then a carry-lookahead resolves the remaining
    unit carries in log₂(L) combine steps instead of L ripple passes.

    EXACTNESS BOUND: limbs up to ~2^24 per plane, NOT arbitrary int32.
    After the single ripple pass a limb of value v leaves carry v>>B
    for its neighbor; the lookahead then resolves only UNIT carries
    (generate/propagate are 0/1 flags), so it is exact iff post-ripple
    limbs are ≤ 2^B (i.e. input limbs < 2^B·(2^B−1)+2^B ≈ 2^24 and no
    limb both generates ≥2 carries and propagates). Every in-repo
    caller feeds relaxed (< 2^13) planes; a future caller with raw
    accumulated planes would pack garbage silently — hence the debug
    check below (enable with PTPU_DEBUG_BOUNDS=1)."""
    if os.environ.get("PTPU_DEBUG_BOUNDS") == "1":
        jax.debug.callback(_assert_relaxed, jnp.max(x))
    x = ripple(x, passes=1)  # limbs ≤ 2^B (≤ 2^B − 1 + carry ≤ 2^B)
    g = (x >> B).astype(jnp.int32)          # generates a carry-out
    a = x & MASK
    p = (a == MASK).astype(jnp.int32)       # propagates an incoming carry
    c_in = _lookahead_chain(g, p)
    out = a + c_in
    # lower limbs masked canonical; the top limb keeps its own high
    # bits (a masked top would silently drop value ≥ 2^264 — lazy NTT
    # outputs legitimately reach there)
    return jnp.concatenate(
        [out[:-1] & MASK, x[-1:] + c_in[-1:]], axis=0)


# --- core multiply ----------------------------------------------------------

def mont_mul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """(L, n) relaxed × (L, n) relaxed → (L, n) relaxed: x·y·R⁻¹ mod p
    by value. CIOS over limb planes with L+1 reduction steps: the output
    value is < p + 2^237, so the top limb plane is ≤ 2^3 pre-ripple (all
    lazy limbs are non-negative, so t[L−1] ≤ value/2^252) and the 2-pass
    ripple cannot push a carry off the truncated top. All intermediates
    stay below 2^31 for limbs < 2^13."""
    # Backend fork, decided at TRACE time: the XLA *CPU* pipeline can
    # spend hours on programs that inline dozens of the unrolled chains
    # below (the quotient kernel inlines ~45 of them), so the CPU
    # backend — the test harness and any jax-on-host fallback — takes
    # the compact fori_loop twin instead. The value semantics are
    # identical (both tested against Python ints); only the TPU path
    # needs the unrolled form's fusion behavior.
    if _unrolled_backend():
        return _mont_mul_unrolled(x, y)
    return mont_mul_compact(x, y)


def _unrolled_backend() -> bool:
    """True when the trace should take the unrolled twin.

    CONTRACT: ``mont_mul`` must only be traced for the process-default
    backend. The choice consults ``jax.default_backend()`` at TRACE
    time, so tracing for a non-default device (``jax.default_device``
    pinning a CPU while a TPU is default) would pick the unrolled form
    on the XLA CPU pipeline — the hours-long-compile hazard this fork
    exists to avoid. No in-repo caller does that (the prover pins the
    whole process to one backend); results would still be correct,
    only compile time is at risk. PTPU_FORCE_COMPACT=1 forces the
    compact twin for such a session."""
    if os.environ.get("PTPU_FORCE_COMPACT") == "1":
        return False
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - uninitialized backend
        return False


def _mont_mul_unrolled(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    n = x.shape[1]
    # STATICALLY UNROLLED over per-plane (n,) arrays: a lax.fori_loop
    # (or any formulation with concatenate/.at[] on the carry state)
    # materializes (L+2, n) through HBM every iteration — measured
    # ~39 ms per (L, 2^20) multiply, ~100x the fused roofline. Pure
    # elementwise ops over plane lists fuse into a handful of kernels
    # with register-resident intermediates. Compile time grows with the
    # 22 inlined steps but is cached (and is a TPU-only cost — see
    # ``mont_mul``).
    xs = [x[i] for i in range(L)]
    ys = [y[j] for j in range(L)]
    zero = jnp.zeros((n,), dtype=jnp.int32)
    t = [zero] * (L + 2)

    def reduce_step(t):
        u = ((t[0] & MASK) * P_INV_NEG) & MASK
        t = [t[j] + u * _P_LIMBS[j] if _P_LIMBS[j] else t[j]
             for j in range(L)] + t[L:]
        carry0 = t[0] >> B
        t = t[1:] + [zero]
        t[0] = t[0] + carry0
        return t

    for i in range(L):
        t = [t[j] + xs[i] * ys[j] for j in range(L)] + t[L:]
        t = reduce_step(t)
    t = reduce_step(t)  # the extra division by 2^B (R = 2^{B(L+1)})
    out = jnp.stack(t[:L], axis=0)
    return ripple(out, passes=2)


def mont_mul_compact(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """``mont_mul`` with the (L+2, n)-state fori_loop formulation.

    ~2x slower than the unrolled ``mont_mul`` on straight-line code,
    but REQUIRED inside lax control-flow bodies (associative_scan /
    scan / fori_loop): the unrolled per-plane version's [1, n] slices
    pick up pathological (8, 128)-tile padding under scan batching —
    a 128x HBM expansion per temporary that OOMs a 16 GB chip."""
    n = x.shape[1]
    p_planes = _const_planes(P, None)
    t = jnp.zeros((L + 2, n), dtype=jnp.int32)

    def reduce_step(t):
        u = ((t[0] & MASK) * P_INV_NEG) & MASK
        t = t.at[:L].add(u[None, :] * p_planes)
        carry0 = t[0] >> B
        t = jnp.concatenate([t[1:], jnp.zeros((1, n), jnp.int32)], axis=0)
        t = t.at[0].add(carry0)
        return t

    def step(i, t):
        xi = lax.dynamic_slice_in_dim(x, i, 1, axis=0)
        t = t.at[:L].add(xi * y)
        return reduce_step(t)

    t = lax.fori_loop(0, L, step, t)
    t = reduce_step(t)
    return ripple(t[:L].astype(jnp.int32), passes=2)


def mont_mul_const(x: jnp.ndarray, c: int) -> jnp.ndarray:
    """x̃ · c̃ with a host-int constant already in the Montgomery domain
    (c = value·R mod p passed as plain int)."""
    return mont_mul(x, _const_planes(c, x.shape[1]))


def add(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Addition: one ripple pass keeps limbs < 2^13. VALUES accumulate
    (no modular reduction) — fine for the butterfly/gate patterns where
    sums feed a ``mont_mul`` (CIOS is exact for values < 2^262) and are
    bounded by ≤ ~30p; not for unbounded accumulation."""
    return ripple(x + y, passes=1)


def sub(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """x − y + 2p. CONTRACT: y's value must be < 2p (a fresh ``mont_mul``
    output or canonical input — exactly the NTT butterfly / gate-term
    shape); x is unconstrained. The result is then non-negative and
    value-correct mod p."""
    two_p = _const_planes(2 * P, None)
    return ripple(x + two_p - y, passes=2)


def neg(x: jnp.ndarray) -> jnp.ndarray:
    """2p − x for x with value < 2p (same contract as ``sub``)."""
    two_p = _const_planes(2 * P, None)
    return ripple(two_p - x, passes=2)


def enter_mont(x_plain: jnp.ndarray) -> jnp.ndarray:
    """Plain (L, n) → Montgomery domain (multiply by R²)."""
    return mont_mul(x_plain, _const_planes(R2_MONT, x_plain.shape[1]))


def exit_mont(x_mont: jnp.ndarray) -> jnp.ndarray:
    """Montgomery (L, n) → plain (multiply by 1)."""
    one = jnp.zeros_like(x_mont).at[0].set(1)
    return mont_mul(x_mont, one)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Relaxed → canonical (< p): full carries + one conditional
    subtract of p (borrows resolved by the same log-depth lookahead as
    ``canon_limbs`` — the former L-pass ripple was ~0.4 s per 2^20
    download conversion)."""
    x = canon_limbs(x)
    p_planes = _const_planes(P, None)
    p_bcast = jnp.broadcast_to(p_planes, x.shape)
    # lexicographic x >= p, top limb down
    gt = jnp.zeros(x.shape[1:], dtype=jnp.bool_)
    eq = jnp.ones(x.shape[1:], dtype=jnp.bool_)
    for i in range(L - 1, -1, -1):
        gt = gt | (eq & (x[i] > p_bcast[i]))
        eq = eq & (x[i] == p_bcast[i])
    geq = gt | eq
    d = x - jnp.where(geq[None], p_bcast, 0)
    # d limbs ∈ (−2^B, 2^B); borrow lookahead: limb borrows when
    # negative, propagates an incoming borrow when exactly zero
    b_in = _lookahead_chain((d < 0).astype(jnp.int32),
                            (d == 0).astype(jnp.int32))
    return (d - b_in) & MASK


# --- batched inverse (Fermat) ----------------------------------------------

def mont_pow_const(x: jnp.ndarray, e: int) -> jnp.ndarray:
    """x̃^e (static exponent), Montgomery domain."""
    nbits = e.bit_length()
    bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], dtype=jnp.int32)
    one_m = _const_planes(R_MONT, x.shape[1])

    def step(i, state):
        acc, base = state
        hit = mont_mul_compact(acc, base)
        acc = jnp.where(bits[i] == 1, hit, acc)
        base = mont_mul_compact(base, base)
        return acc, base

    acc, _ = lax.fori_loop(0, nbits, step, (one_m, x))
    return acc


def inv(x: jnp.ndarray) -> jnp.ndarray:
    """Batched x̃⁻¹ (0 → 0) via Fermat."""
    return mont_pow_const(x, P - 2)


def batch_inv(x: jnp.ndarray) -> jnp.ndarray:
    """Montgomery-trick batched inverse over the lane axis: two
    associative prefix-product scans + ONE Fermat inversion, ~2·n·log n
    multiplies instead of 254·n. All inputs must be nonzero."""
    n = x.shape[1]

    def combine(a, b):
        return mont_mul_compact(a, b)

    pre = lax.associative_scan(combine, x, axis=1)          # Πx_{≤i}
    suf = lax.associative_scan(combine, x[:, ::-1], axis=1)[:, ::-1]
    total_inv = mont_pow_const(pre[:, -1:], P - 2)          # (L, 1)
    one_m = _const_planes(R_MONT, 1)
    pre_prev = jnp.concatenate(
        [jnp.broadcast_to(one_m, (L, 1)), pre[:, :-1]], axis=1)
    suf_next = jnp.concatenate(
        [suf[:, 1:], jnp.broadcast_to(one_m, (L, 1))], axis=1)
    out = mont_mul(pre_prev, suf_next)
    return mont_mul(out, jnp.broadcast_to(total_inv, (L, n)))


# --- MXU plane interface ----------------------------------------------------

def to_mxu_planes(x: jnp.ndarray) -> jnp.ndarray:
    """(L, n) relaxed → (L6, n) int8 canonical 6-bit planes."""
    x = canon_limbs(x)
    lo = (x & MASK6).astype(jnp.int8)
    hi = (x >> B6).astype(jnp.int8)
    return jnp.stack([lo, hi], axis=1).reshape(L6, *x.shape[1:])


def reduce_mxu_planes(planes: jnp.ndarray) -> jnp.ndarray:
    """(K, …) int32 lazy base-2^6 planes (each < 2^31) → (L, …) relaxed
    12-bit planes, value-exact mod p.

    Carry-propagates base-64 planes, regroups into 12-bit limbs, then
    folds everything above limb L−1 with hi·R ≡ hi·R²·R⁻¹ (one CIOS)."""
    K = planes.shape[0]
    t = planes
    # base-64 carries: excess shrinks 64× per pass; 2^31 → <2^6+1 in 5
    ext = 5  # room for carries walking past the top plane
    t = jnp.concatenate(
        [t, jnp.zeros((ext,) + t.shape[1:], jnp.int32)], axis=0)
    for _ in range(6):
        carry = t >> B6
        t = (t & MASK6) + jnp.concatenate(
            [jnp.zeros((1,) + t.shape[1:], jnp.int32), carry[:-1]], axis=0)
    K2 = t.shape[0]
    if K2 % 2:
        t = jnp.concatenate(
            [t, jnp.zeros((1,) + t.shape[1:], jnp.int32)], axis=0)
        K2 += 1
    # regroup pairs of 6-bit planes into 12-bit limbs
    t12 = t.reshape(K2 // 2, 2, *t.shape[1:])
    t12 = t12[:, 0] + (t12[:, 1] << B6)
    # fold chunks of L limbs: value = Σ_c 2^{264·c}·chunk_c; each chunk
    # above the first folds via mont_mul with Cc = 2^{264·c}·R (so the
    # R⁻¹ cancels and the product is the plain shifted value)
    n12 = t12.shape[0]
    acc = None
    for c in range(0, (n12 + L - 1) // L):
        chunk = t12[c * L : (c + 1) * L]
        if chunk.shape[0] < L:
            chunk = jnp.concatenate(
                [chunk,
                 jnp.zeros((L - chunk.shape[0],) + chunk.shape[1:],
                           jnp.int32)], axis=0)
        if c == 0:
            acc = chunk
            continue
        cc = pow(2, 264 * c, P) * R_MONT % P
        flat = chunk.reshape(L, -1)
        folded = mont_mul(flat, _const_planes(cc, flat.shape[1]))
        acc = ripple(acc + folded.reshape((L,) + chunk.shape[1:]), passes=2)
    return acc


# --- compact 16-bit storage (device-resident ext arrays) -------------------

def pack16(x: jnp.ndarray) -> jnp.ndarray:
    """(L, n) planes with value < 2^256 → (16, n) uint16 value planes.

    CONTRACT: the input's represented VALUE must be < 2^256 (e.g. any
    mont_mul output, < 2p). A *lazy* limb-plane value (a raw
    ``reduce_mxu_planes``/NTT output, limbs < 2^13 across all 22
    planes) can reach ~2^264 and silently loses its top bits here —
    callers must normalize first with ``mont_mul_const(x, R_MONT)``
    (value-preserving fold into [0, 2p)), as ``_ext_chunk_impl`` does.
    Limbs must additionally be RELAXED (< 2^13 — every mont_mul/ripple
    output is): ``canon_limbs``'s lookahead assumes unit carries, so an
    arbitrary int32 plane would pack garbage where the old 18-pass
    resolver merely truncated.

    After full carry propagation the 12-bit limbs are CANONICAL, so the
    value's binary expansion is their concatenation — each 16-bit
    window is a pure bit-slice of at most two adjacent limbs, no carry
    resolution at all (the former 18-pass base-2^16 ripple cost more
    device time than the NTT feeding it). Halves the HBM footprint of
    resident arrays."""
    return _pack16_slices(canon_limbs(x))


def _pack16_slices(x: jnp.ndarray) -> jnp.ndarray:
    """(L, n) CANONICAL limbs → (16, n) uint16 bit-slices — the pack16
    core, callable directly on already-canonical data (the download
    wire path slices ``canonical()`` output without a redundant second
    canonicalization)."""
    outs = []
    for t in range(16):
        bit = 16 * t
        a, s = bit // B, bit % B  # window starts inside limb a at bit s
        # s ∈ {0, 4, 8} for B=12, so two limbs always cover a window
        w = x[a] >> s
        if a + 1 < L:
            w = w | (x[a + 1] << (B - s))
        outs.append(w & 0xFFFF)
    return jnp.stack(outs, axis=0).astype(jnp.uint16)


def unpack16(x16: jnp.ndarray) -> jnp.ndarray:
    """(16, n) uint16 → (L, n) int32 canonical 12-bit limbs."""
    w = x16.astype(jnp.int32)
    outs = []
    for i in range(L):
        bit = B * i
        t, s = bit // 16, bit % 16
        v = w[t] >> s
        if s > 4 and t + 1 < 16:
            v = v | (w[t + 1] << (16 - s))
        outs.append(v & MASK)
    return jnp.stack(outs, axis=0)


# --- host-side reference (tests) -------------------------------------------

def planes_to_ints(planes) -> list:
    """(L, n) planes (any laziness) → Python ints (not reduced mod p)."""
    planes = np.asarray(planes)
    out = []
    for j in range(planes.shape[1]):
        out.append(sum(int(planes[i, j]) << (B * i)
                       for i in range(planes.shape[0])))
    return out


def ints_to_planes(vals) -> np.ndarray:
    out = np.zeros((L, len(vals)), dtype=np.int32)
    for j, v in enumerate(vals):
        v = int(v)
        for i in range(L):
            out[i, j] = (v >> (B * i)) & MASK
    return out
