"""Batched big-prime field arithmetic on TPU (jnp, int32 limbs).

BASELINE.json config 5: "eigentrust-zk witness gen, batched BN254 field
ops on TPU, bit-exact field scores". The reference does all field math
in scalar Rust (ff 4×u64 Montgomery, e.g. the converge hot loop
``dynamic_sets/native.rs:319-329`` and per-cell witness inverses
``dynamic_sets/mod.rs:126-181``); here the same arithmetic runs
data-parallel over a batch dimension so large witness pipelines (hashes,
score products, inverse chains) are one TPU dispatch, not N scalar ops.

Representation: a field element is a row of ``L`` little-endian limbs of
``B`` bits in int32. B=12, L=22 (264 bits ≥ 254-bit moduli) keeps every
intermediate of the Montgomery CIOS inner loop below 2^31:

- per-step products are < 2^24,
- limbs accumulate lazily across the 22 CIOS steps (bounded by
  22·2^25 < 2^30) — no per-step carry propagation,
- the shifted-out limb's low bits are exact despite deferred carries,
  because t ≡ t[0] (mod 2^B) (all other limbs carry factors of 2^B).

Everything is modulus-generic (BN254 Fr/Fq, secp256k1 field and order —
any prime up to 256 bits): precompute a ``FieldCtx`` per modulus. All ops are
shape-static, jit-compatible, int32-only (TPU-native); bit-exactness
against Python ints is the test contract (``tests/test_fieldops.py``).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

LIMB_BITS = 12
NUM_LIMBS = 22
BASE = 1 << LIMB_BITS
MASK = BASE - 1


class FieldCtx:
    """Per-modulus constants, host-side. Hashable/static for jit."""

    def __init__(self, modulus: int):
        # CIOS is exact for any modulus < R = 2^264: with input x < R the
        # output is < p·(x/R + 1) < 2p, which one conditional subtract
        # fixes. 256 bits leaves ≥ 2^8 of lazy-sum headroom (see
        # ``max_lazy_terms``) — enough for BN254 Fr/Fq AND the secp256k1
        # field/order the batched-ECDSA path needs.
        if modulus.bit_length() > 256:
            raise ValueError("modulus too large for the limb layout")
        self.modulus = modulus
        # how many < p terms may be lazily summed before exceeding R
        self.max_lazy_terms = 1 << (LIMB_BITS * NUM_LIMBS
                                    - modulus.bit_length())
        self.p_limbs = tuple(
            (modulus >> (LIMB_BITS * i)) & MASK for i in range(NUM_LIMBS)
        )
        # -p^{-1} mod 2^B (CIOS quotient constant)
        self.p_inv_neg = (-pow(modulus, -1, BASE)) % BASE
        self.r = pow(2, LIMB_BITS * NUM_LIMBS, modulus)  # R mod p
        self.r2 = self.r * self.r % modulus  # R² mod p (to-Montgomery factor)

    def __hash__(self):
        return hash(self.modulus)

    def __eq__(self, other):
        return isinstance(other, FieldCtx) and other.modulus == self.modulus


# --- host <-> limb conversion ----------------------------------------------

def to_limbs(values) -> np.ndarray:
    """Python ints → (n, L) int32 limb rows (plain, not Montgomery).

    Fast path: serialize through ``int.to_bytes`` and split 3 bytes →
    two 12-bit limbs vectorized (the per-int double loop was ~0.6 s per
    32k×4 ingest chunk — wall-clock at 1M-attestation scale). Values
    outside [0, 2^264) (never produced by the field paths) fall back to
    the per-limb masking loop."""
    vals = [int(v) for v in values]
    n = len(vals)
    try:
        buf = b"".join(v.to_bytes(33, "little") for v in vals)
    except (OverflowError, ValueError):  # negative or >= 2^264
        out = np.zeros((n, NUM_LIMBS), dtype=np.int32)
        for i, v in enumerate(vals):
            for j in range(NUM_LIMBS):
                out[i, j] = (v >> (LIMB_BITS * j)) & MASK
        return out
    raw = np.frombuffer(buf, dtype=np.uint8).reshape(n, 33)
    b = raw.reshape(n, 11, 3).astype(np.int32)
    out = np.empty((n, NUM_LIMBS), dtype=np.int32)
    out[:, 0::2] = b[:, :, 0] | ((b[:, :, 1] & 0xF) << 8)
    out[:, 1::2] = (b[:, :, 1] >> 4) | (b[:, :, 2] << 4)
    return out


def from_limbs(arr) -> list:
    """(n, L) limb rows → Python ints (vectorized repack for normalized
    rows; arbitrary/unnormalized limbs take the exact summation path)."""
    arr = np.asarray(arr)
    n = arr.shape[0]
    if n and ((arr < 0) | (arr > MASK)).any():
        return [
            sum(int(arr[i, j]) << (LIMB_BITS * j)
                for j in range(NUM_LIMBS))
            for i in range(n)
        ]
    b = np.empty((n, 33), dtype=np.uint8)
    l0 = arr[:, 0::2]
    l1 = arr[:, 1::2]
    b[:, 0::3] = l0 & 0xFF
    b[:, 1::3] = (l0 >> 8) | ((l1 & 0xF) << 4)
    b[:, 2::3] = l1 >> 4
    by = b.tobytes()
    return [int.from_bytes(by[33 * i:33 * (i + 1)], "little")
            for i in range(n)]


# --- carry handling ---------------------------------------------------------

def _ripple(t: jnp.ndarray) -> jnp.ndarray:
    """Normalize limbs to [0, 2^B): full-length carry/borrow ripple.

    A single carry can cascade across every limb (…FFF + 1), so the pass
    count is L. Works for negative limbs too: int32 ``>>`` is arithmetic
    and ``& MASK`` of a negative limb yields its low bits, which is
    exactly the borrow decomposition d = (d >> B)·2^B + (d & MASK)."""
    width = t.shape[1]
    for _ in range(width):
        carry = t >> LIMB_BITS
        t = (t & MASK) + jnp.pad(carry[:, :-1], ((0, 0), (1, 0)))
    return t


def _geq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Rowwise a >= b on normalized limb rows (top-down lexicographic);
    b may be a (L,) constant row or an (n, L) batch."""
    b = jnp.broadcast_to(b, a.shape)
    n = a.shape[0]
    gt = jnp.zeros((n,), dtype=jnp.bool_)
    eq = jnp.ones((n,), dtype=jnp.bool_)
    for j in range(NUM_LIMBS - 1, -1, -1):
        gt = gt | (eq & (a[:, j] > b[:, j]))
        eq = eq & (a[:, j] == b[:, j])
    return gt | eq


def _p_row(ctx: FieldCtx) -> jnp.ndarray:
    return jnp.asarray(ctx.p_limbs, dtype=jnp.int32)


def _cond_sub_p(t: jnp.ndarray, ctx: FieldCtx) -> jnp.ndarray:
    """One conditional subtract of p (inputs normalized, in [0, 2p))."""
    p_row = _p_row(ctx)
    sub = _geq(t, p_row)
    return _ripple(t - jnp.where(sub[:, None], p_row, 0))


# --- core Montgomery multiply ----------------------------------------------

@partial(jax.jit, static_argnames=("ctx",))
def mont_mul(ctx: FieldCtx, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Batched Montgomery product: x·y·R⁻¹ mod p, normalized rows.

    x may hold lazily-summed values up to R = 2^264 (see
    ``mont_matvec``): the CIOS output is < p·(x/R + 1) < 2p for any
    x < R, so the single conditional subtract suffices."""
    n = x.shape[0]
    p_row = _p_row(ctx)
    t = jnp.zeros((n, NUM_LIMBS + 2), dtype=jnp.int32)

    def step(i, t):
        xi = lax.dynamic_slice_in_dim(x, i, 1, axis=1)  # (n, 1)
        t = t.at[:, :NUM_LIMBS].add(xi * y)
        u = ((t[:, 0] & MASK) * ctx.p_inv_neg) & MASK  # (n,)
        t = t.at[:, :NUM_LIMBS].add(u[:, None] * p_row)
        # t ≡ 0 mod 2^B now; shift one limb down, keeping the carry exact
        carry0 = t[:, 0] >> LIMB_BITS
        t = jnp.pad(t[:, 1:], ((0, 0), (0, 1)))
        t = t.at[:, 0].add(carry0)
        return t

    t = lax.fori_loop(0, NUM_LIMBS, step, t)
    t = _ripple(t)[:, :NUM_LIMBS]
    return _cond_sub_p(t, ctx)


@partial(jax.jit, static_argnames=("ctx",))
def add_mod(ctx: FieldCtx, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """(x + y) mod p on normalized rows (works in either domain)."""
    return _cond_sub_p(_ripple(x + y), ctx)


@partial(jax.jit, static_argnames=("ctx",))
def sub_mod(ctx: FieldCtx, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """(x - y) mod p on normalized rows (works in either domain)."""
    need_p = ~_geq(x, y)
    return _ripple(x - y + jnp.where(need_p[:, None], _p_row(ctx), 0))


def to_mont(ctx: FieldCtx, limbs: jnp.ndarray) -> jnp.ndarray:
    """Plain rows → Montgomery domain (multiply by R² with reduction)."""
    r2 = jnp.broadcast_to(
        jnp.asarray(to_limbs([ctx.r2])[0], dtype=jnp.int32), limbs.shape
    )
    return mont_mul(ctx, limbs, r2)


def from_mont(ctx: FieldCtx, limbs: jnp.ndarray) -> jnp.ndarray:
    """Montgomery rows → plain rows (multiply by 1 with reduction)."""
    one = jnp.zeros_like(limbs).at[:, 0].set(1)
    return mont_mul(ctx, limbs, one)


def mont_one(ctx: FieldCtx, n: int) -> jnp.ndarray:
    """1 in Montgomery form, broadcast to (n, L)."""
    return jnp.broadcast_to(
        jnp.asarray(to_limbs([ctx.r])[0], dtype=jnp.int32), (n, NUM_LIMBS)
    )


@partial(jax.jit, static_argnames=("ctx", "exp"))
def mont_pow(ctx: FieldCtx, x: jnp.ndarray, exp: int) -> jnp.ndarray:
    """x^exp (static exponent) in the Montgomery domain.

    Small exponents (the Poseidon S-box x^5) unroll to a minimal
    multiply chain; large ones (Fermat inversion, ~254 bits) run a
    rolled square-and-multiply under ``fori_loop`` — the unrolled chain
    would be ~380 multiplies of ~22 ops each, minutes of XLA compile for
    zero runtime benefit."""
    e = int(exp)
    if e.bit_length() <= 8:
        acc = mont_one(ctx, x.shape[0])
        base = x
        while e:
            if e & 1:
                acc = mont_mul(ctx, acc, base)
            e >>= 1
            if e:
                base = mont_mul(ctx, base, base)
        return acc

    nbits = e.bit_length()
    bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], dtype=jnp.int32)

    def step(i, state):
        acc, base = state
        hit = mont_mul(ctx, acc, base)
        acc = jnp.where(bits[i] == 1, hit, acc)
        base = mont_mul(ctx, base, base)
        return acc, base

    acc, _ = lax.fori_loop(0, nbits, step, (mont_one(ctx, x.shape[0]), x))
    return acc


def inv_mod(ctx: FieldCtx, x: jnp.ndarray) -> jnp.ndarray:
    """Batched modular inverse via Fermat (x^(p-2)); 0 → 0 like the
    reference's witness convention for absent inverses."""
    return mont_pow(ctx, x, ctx.modulus - 2)


# --- batched dot products (the field-converge building block) --------------

@partial(jax.jit, static_argnames=("ctx",))
def mont_matvec(ctx: FieldCtx, m: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """new[i] = Σ_j m[j, i] · v[j]  (mod p), Montgomery domain.

    m: (N, N, L) trust matrix, v: (N, L) — index convention matches the
    reference converge loop (``dynamic_sets/native.rs:322-326``: score
    flows j → i through m[j][i]). The N² products run as one batched
    Montgomery multiply; the lazy limb sum over j is exact for N ≤ 512
    (sum < 512·p keeps CIOS intermediates in int32 and its output < 2p).
    """
    n = m.shape[0]
    limit = min(512, ctx.max_lazy_terms)
    if n > limit:
        raise ValueError(
            f"mont_matvec supports set sizes up to {limit} for this modulus")
    prod = mont_mul(
        ctx,
        m.transpose(1, 0, 2).reshape(n * n, NUM_LIMBS),  # [i, j] rows
        jnp.tile(v, (n, 1)),
    ).reshape(n, n, NUM_LIMBS)
    acc = _ripple(jnp.sum(prod, axis=1, dtype=jnp.int32))
    # acc < N·p: one Montgomery multiply by R (plain) maps it to
    # acc·R·R⁻¹ = acc mod p while staying in the Montgomery domain
    r_row = jnp.broadcast_to(
        jnp.asarray(to_limbs([ctx.r])[0], dtype=jnp.int32), acc.shape
    )
    return mont_mul(ctx, acc, r_row)


# --- bit-exact EigenTrust field convergence --------------------------------

def _lazy_rowsum_mod(ctx: FieldCtx, rows: jnp.ndarray) -> jnp.ndarray:
    """Exact mod-p reduction of a lazy limb-sum (< 512·p): one
    Montgomery multiply by plain R maps acc → acc·R·R⁻¹ = acc mod p."""
    r_row = jnp.broadcast_to(
        jnp.asarray(to_limbs([ctx.r])[0], dtype=jnp.int32), rows.shape
    )
    return mont_mul(ctx, rows, r_row)


@partial(jax.jit, static_argnames=("ctx", "num_iterations"))
def _field_converge_mont(ctx: FieldCtx, m: jnp.ndarray, s0: jnp.ndarray,
                         num_iterations: int):
    n = m.shape[0]
    # row sums + Fermat inverse-or-zero (native.rs:305-314 semantics)
    row_sum = _lazy_rowsum_mod(ctx, _ripple(jnp.sum(m, axis=1,
                                                    dtype=jnp.int32)))
    inv = inv_mod(ctx, row_sum)  # (N, L); zero rows stay zero
    m_norm = mont_mul(
        ctx,
        m.reshape(n * n, NUM_LIMBS),
        jnp.repeat(inv, n, axis=0),
    ).reshape(n, n, NUM_LIMBS)

    def body(_, s):
        return mont_matvec(ctx, m_norm, s)

    return lax.fori_loop(0, num_iterations, body, s0)


def field_converge(ctx: FieldCtx, matrix, initial, num_iterations: int) -> list:
    """Bit-exact TPU twin of ``EigenTrustSet.converge``'s post-filter
    phase (``models/eigentrust.py`` / reference
    ``dynamic_sets/native.rs:305-329``): field row-normalization by
    modular inverse-or-zero, then the fixed power iteration — producing
    the exact same Fr scores as the scalar loop, but as batched int32
    limb arithmetic on device (the zk witness path of BASELINE.json
    config 5).

    ``matrix``: N×N ints (filtered opinion values), ``initial``: N ints.
    Returns N ints.
    """
    n = len(matrix)
    flat = [int(v) % ctx.modulus for row in matrix for v in row]
    m = to_mont(ctx, jnp.asarray(to_limbs(flat))).reshape(n, n, NUM_LIMBS)
    s0 = to_mont(ctx, jnp.asarray(to_limbs([int(v) for v in initial])))
    s = _field_converge_mont(ctx, m, s0, num_iterations)
    return from_limbs(np.asarray(from_mont(ctx, s)))
