"""Device-resident NTT over BN254 Fr — four-step matmul formulation.

The number-theoretic transforms dominating PLONK proving (SURVEY/VERDICT
round 1: 14 forward 8n-coset NTTs + the 8n inverse per proof) run here
as MXU matmuls instead of host butterflies:

    X[k1 + k2·A] = Σ_{j2} ω^{A·j2·k2} · ( ω^{j2·k1} ·
                   Σ_{j1} ω^{B·j1·k1} · x[j1·B + j2] ),   N = A·B

Both inner sums are length-≤2048 NTTs applied to every row/column at
once — (A×A)@(A×B) field matmuls. A field matmul decomposes into 6-bit
limb planes multiplied as *exact f32* MXU matmuls (6+6+11 ≤ 24 mantissa
bits; the f32 path measures ~32 TFLOPs on v5e vs ~18 TOPs for int8
through XLA) and re-assembled by ``fieldops2.reduce_mxu_planes``. Data
stays in the Montgomery domain; the W matrices are plain-valued, so a
stage matmul maps Montgomery inputs to Montgomery outputs with no extra
R factors.

The 8n extension domain is handled as 8 independent size-n coset NTTs
(shift·ω₈ⁿ-cosets) plus a cross-chunk radix-8 combine for the inverse —
every plan stays n-sized, so the same machinery scales from k=14 tests
to the k=22 flagship without 8192-wide W matrices.

Forward output (and inverse input) use the "FS layout": element
X[k1 + k2·A] lives at flat position k1·B + k2. Pointwise consumers (the
quotient kernel) never notice; ``intt`` inverts the layout back to
natural coefficient order.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.fields import BN254_FR_MODULUS as P
from . import fieldops2 as f2

L, L6 = f2.L, f2.L6


def _root_of_unity(k: int) -> int:
    """Primitive 2^k-th root of unity in Fr (matches zk/domain.py)."""
    # 5 generates the full multiplicative group quotient; 2-adicity 28
    g = pow(5, (P - 1) >> 28, P)
    return pow(g, 1 << (28 - k), P)


def _mont(v: int) -> int:
    return v * f2.R_MONT % P


class NttPlan:
    """Per-k device tables: stage matrices as 6-bit int8 planes and the
    cross twiddle as packed uint16 Montgomery planes. ~0.3 GB at k=20.
    Build happens on device (uploading only A+B generator scalars)."""

    _cache: dict = {}

    def __init__(self, k: int):
        self.k = k
        self.n = 1 << k
        a = (k + 1) // 2
        self.A, self.B = 1 << a, 1 << (k - a)
        omega = _root_of_unity(k)
        self.omega = omega
        w_a = pow(omega, self.B, P)   # order A
        w_b = pow(omega, self.A, P)   # order B
        self.W_A = self._build_w(w_a, self.A)
        self.W_B = self._build_w(w_b, self.B)
        # the stage matrices invert by row-flip (their roots have order
        # = size), but the cross twiddle's root ω has order N, so the
        # inverse needs its own table built from ω⁻¹
        self.T16 = self._build_t(omega)
        self.T16_inv = self._build_t(pow(omega, -1, P))
        self.n_inv_mont = _mont(pow(self.n, -1, P))

    @classmethod
    def get(cls, k: int) -> "NttPlan":
        plan = cls._cache.get(k)
        if plan is None:
            plan = cls._cache[k] = cls(k)
        return plan

    @staticmethod
    def _pow_table_scan(gen_mont: jnp.ndarray, cols: int) -> jnp.ndarray:
        """rows of powers: out[:, c] = gen^c (Montgomery), via a scan.
        gen_mont: (L, rows). Returns (cols, L, rows) int32."""
        rows = gen_mont.shape[1]
        one = f2._const_planes(f2.R_MONT, rows)

        def step(carry, _):
            nxt = f2.mont_mul_compact(carry, gen_mont)
            return nxt, carry

        _, ys = lax.scan(step, one, None, length=cols)
        return ys

    def _build_w(self, w_root: int, size: int) -> jnp.ndarray:
        """(L6, size, size) int8 plain planes of W[r, c] = w_root^{r·c}."""
        gens = [pow(w_root, r, P) for r in range(size)]
        gen_mont = jnp.asarray(
            f2.ints_to_planes([_mont(g) for g in gens]))

        @jax.jit
        def build(gen_mont):
            cols = self._pow_table_scan(gen_mont, size)  # (c, L, r) Mont
            flat = jnp.moveaxis(cols, 0, 2).reshape(L, size * size)
            plain = f2.exit_mont(flat)
            return f2.to_mxu_planes(plain).reshape(L6, size, size)

        return build(gen_mont)

    def _build_t(self, omega: int) -> jnp.ndarray:
        """(16, A, B) uint16 packed Montgomery planes of the cross
        twiddle T[k1, j2] = ω^{k1·j2}."""
        gens = [pow(omega, k1, P) for k1 in range(self.A)]
        gen_mont = jnp.asarray(
            f2.ints_to_planes([_mont(g) for g in gens]))

        @jax.jit
        def build(gen_mont):
            cols = self._pow_table_scan(gen_mont, self.B)  # (j2, L, k1)
            flat = jnp.moveaxis(cols, 0, 2).reshape(L, self.A * self.B)
            return f2.pack16(flat).reshape(16, self.A, self.B)

        return build(gen_mont)


def _plane_matmul_left(w_planes: jnp.ndarray, x6: jnp.ndarray) -> jnp.ndarray:
    """Σ_j W[i, j]·X[j, c] over 6-bit planes: w_planes (L6, A, A) int8,
    x6 (L6, A, C) int8 → (L, A, C) Montgomery relaxed planes."""
    n_out = 2 * L6 - 1
    A = x6.shape[1]
    C = x6.shape[2]
    xf = x6.astype(jnp.float32).transpose(1, 0, 2).reshape(A, L6 * C)
    out = jnp.zeros((n_out, A, C), dtype=jnp.int32)
    for m in range(L6):
        wf = w_planes[m].astype(jnp.float32)
        cm = jax.lax.dot_general(
            wf, xf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        cm = cm.astype(jnp.int32).reshape(A, L6, C).transpose(1, 0, 2)
        out = out.at[m : m + L6].add(cm)
    return f2.reduce_mxu_planes(out.reshape(n_out, A * C)).reshape(L, A, C)


def _plane_accum_right(x6: jnp.ndarray, w_planes: jnp.ndarray) -> jnp.ndarray:
    """LAZY stage of the right plane-matmul: Σ_j X[r, j]·W[i, j] as
    (2·L6−1, A, out) int32 plane accumulations, NOT yet reduced mod p.
    x6 (L6, A, B_in) int8; w_planes (L6, out, B_in) int8 (W[out, in]).
    Shared by the single-chip kernel below and the sharded NTT
    (parallel/ntt.py), whose per-device partials psum to exactly this
    total — the exact-f32 / int32 bound analysis lives in ONE place."""
    n_out = 2 * L6 - 1
    _, A, Bd = x6.shape
    out_dim = w_planes.shape[1]
    xf = x6.astype(jnp.float32).reshape(L6 * A, Bd)
    out = jnp.zeros((n_out, A, out_dim), dtype=jnp.int32)
    for m in range(L6):
        wf = w_planes[m].astype(jnp.float32)  # (out, in)
        cm = jax.lax.dot_general(
            xf, wf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        cm = cm.astype(jnp.int32).reshape(L6, A, out_dim)
        out = out.at[m : m + L6].add(cm)
    return out


def _plane_matmul_right(x6: jnp.ndarray, w_planes: jnp.ndarray) -> jnp.ndarray:
    """Σ_j X[r, j]·W[i, j] over planes: x6 (L6, A, B) int8, w_planes
    (L6, B, B) int8 (indexed W[out, in]) → (L, A, B) Montgomery
    relaxed."""
    _, A, Bd = x6.shape
    out = _plane_accum_right(x6, w_planes)
    return f2.reduce_mxu_planes(out.reshape(out.shape[0], A * Bd)).reshape(
        L, A, Bd)


def _flip_rows(planes: jnp.ndarray) -> jnp.ndarray:
    """index map r → (size − r) mod size on axis 1: turns W into W⁻¹
    (ω^{-rc} = ω^{(size−r)c}) without storing a second table."""
    head = planes[:, :1]
    tail = planes[:, 1:][:, ::-1]
    return jnp.concatenate([head, tail], axis=1)


@jax.jit
def _ntt_impl(x, w_a, w_b, t16):
    A = w_a.shape[1]
    B = w_b.shape[1]
    x6 = f2.to_mxu_planes(x).reshape(L6, A, B)
    y = _plane_matmul_left(w_a, x6)                  # (L, A, B) [k1, j2]
    tw = f2.unpack16(t16.reshape(16, A * B)).reshape(L, A, B)
    y = f2.mont_mul(y.reshape(L, A * B), tw.reshape(L, A * B))
    y6 = f2.to_mxu_planes(y).reshape(L6, A, B)
    z = _plane_matmul_right(y6, w_b)                 # (L, A, B) [k1, k2]
    return z.reshape(L, A * B)


@jax.jit
def _intt_impl(z, w_a, w_b, t16_inv, n_inv_planes):
    A = w_a.shape[1]
    B = w_b.shape[1]
    z6 = f2.to_mxu_planes(z).reshape(L6, A, B)
    y = _plane_matmul_right(z6, _flip_rows(w_b))     # (L, A, B) [k1, j2]
    t_inv = f2.unpack16(t16_inv.reshape(16, A * B)).reshape(L, A, B)
    y = f2.mont_mul(y.reshape(L, A * B), t_inv.reshape(L, A * B))
    y6 = f2.to_mxu_planes(y).reshape(L6, A, B)
    out = _plane_matmul_left(_flip_rows(w_a), y6)    # (L, j1, j2)
    out = out.reshape(L, A * B)
    return f2.mont_mul(out, n_inv_planes)


def ntt(x: jnp.ndarray, plan: NttPlan) -> jnp.ndarray:
    """Forward NTT: (L, n) Montgomery planes, natural order → FS layout
    (element X[k1 + k2·A] at flat k1·B + k2)."""
    return _ntt_impl(x, plan.W_A, plan.W_B, plan.T16)


def intt(z: jnp.ndarray, plan: NttPlan) -> jnp.ndarray:
    """Inverse NTT: FS layout → natural coefficient order (scaled n⁻¹)."""
    n_inv = f2._const_planes(plan.n_inv_mont, 1)
    return _intt_impl(z, plan.W_A, plan.W_B, plan.T16_inv, n_inv)
