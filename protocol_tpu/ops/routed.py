"""Clos-routed sparse converge — the streaming SpMV for large trust graphs.

The gather-SpMV in ``ops.converge`` pays ~7 ns per edge on TPU (XLA's
general gather runs on the scalar unit). This module reformulates the
power iteration so that *no general gather appears at all*:

1. **broadcast** (streaming/MXU): edge values ``s[src]·w`` materialize in
   source-major order — block-diagonal expansion matmuls broadcast each
   node's score across its out-row lanes;
2. **route** (streaming): the edge array moves from source-major to
   destination-major order through a Clos network of lane permutations
   and transposes (``ops.clos``) — the sparse-matrix transpose as a
   permutation-network program;
3. **reduce** (streaming/MXU): lane-segmented sums collapse each
   destination row, and the per-node totals route back to state order
   through a second (node-sized) Clos network; the dangling-mass rank-1
   correction and pre-trust damping are elementwise.

Semantics are identical to ``ops.converge.spmv`` (same filtering,
normalization, redistribution — ``dynamic_sets/native.rs:234-337``).

**Memory layout rule** (the reason for the blocked representation): XLA
tiles the last two dims of every array as (8, 128); a ``[rows, 8]``
bucket array would be padded 16× in HBM — fatal at 2^28 slots. So every
large array here is either 1-D or ``[X, 128]`` with ``X ≡ 0 (mod 8)``:
a width-w < 128 bucket packs ``g = 128/w`` logical rows per lane-row,
its row-adjacent values live in lane runs, and the per-row broadcast/
reduce are contractions with constant ``[g, 128]`` / ``[128, g]``
0/1 block matrices. Row positions in the state/z vectors are
column-major in the ``[g, X]`` grid so the skinny operands of those
contractions reshape without padded copies.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .clos import _apply_route_jit, _use_pallas, plan_route, plan_routes
from .converge import (
    Semiring,
    adaptive_loop,
    dangling_and_damping,
    semiring_tail,
)
from ..graph import filter_edges, stable_argsort_bounded

__all__ = [
    "RoutedOperator",
    "build_routed_operator",
    "ensure_edge_slots",
    "routed_arrays",
    "RoutedStatic",
    "spmv_routed",
    "converge_routed_fixed",
    "converge_routed_adaptive",
    "spmv_routed_semiring",
    "converge_routed_fixed_semiring",
    "converge_routed_adaptive_semiring",
    "converge_routed_topics",
]


def _ceil_pow2_exp(x: int, floor: int = 7) -> int:
    e = floor
    while (1 << e) < x:
        e += 1
    return e


def _initial_scores(valid: np.ndarray, initial: float, dtype) -> np.ndarray:
    return (valid * initial).astype(dtype)


def _scores_for_nodes(state_to_node: np.ndarray, n: int,
                      state_scores) -> np.ndarray:
    state_scores = np.asarray(state_scores)
    out = np.zeros(n, dtype=state_scores.dtype)
    live = state_to_node >= 0
    out[state_to_node[live]] = state_scores[live]
    return out


def _scores_from_nodes(state_to_node: np.ndarray, valid: np.ndarray,
                       node_scores, dtype) -> np.ndarray:
    """Inverse of ``_scores_for_nodes``: scatter a node-order vector into
    state-slot order (dead slots stay 0) — the warm-start seam for the
    routed engines (a previous converge's node scores restart the next)."""
    node_scores = np.asarray(node_scores, dtype=np.float64)
    out = np.zeros(len(state_to_node), dtype=np.float64)
    live = state_to_node >= 0
    out[live] = node_scores[state_to_node[live]]
    return (out * valid).astype(dtype)


def blocked_broadcast(arrs: dict, s, widths: tuple, xs: tuple,
                      total_len: int):
    """Expand a state(-slice) vector into weighted edge values across the
    blocked buckets: the shared source side of the routed SpMV (used by
    the single-device and the per-shard kernels)."""
    parts = []
    pos = 0
    for bi, (w, X) in enumerate(zip(widths, xs)):
        w_mat = arrs["out_weight"][bi]
        if w < 128:
            g = 128 // w
            s2t = lax.slice_in_dim(s, pos, pos + g * X).reshape(g, X)
            v = jnp.einsum("gl,gx->xl", arrs["out_expand"][bi], s2t,
                           precision=_PREC) * w_mat
            pos += g * X
        else:
            nb_pad = X * 128 // w        # padded row count
            rows = lax.slice_in_dim(s, pos, pos + nb_pad)
            v = jnp.broadcast_to(
                rows[:, None], (nb_pad, w // 128)).reshape(X, 1) * w_mat
            pos += nb_pad
        parts.append(v.reshape(-1))
    used = sum(X * 128 for X in xs)
    parts.append(jnp.zeros((total_len - used,), dtype=s.dtype))
    return jnp.concatenate(parts)


def blocked_reduce(arrs: dict, y, widths: tuple, xs: tuple, n_pos: int,
                   total_len: int):
    """Lane-segmented per-row sums of a routed edge array: the shared
    destination side of the routed SpMV."""
    sums = []
    off = 0
    for bi, (w, X) in enumerate(zip(widths, xs)):
        y2 = lax.slice_in_dim(y, off, off + X * 128).reshape(X, 128)
        if w < 128:
            z2 = jnp.einsum("xl,gl->gx", y2, arrs["in_reduce"][bi],
                            precision=_PREC)
            sums.append(z2.reshape(-1))
        else:
            nb_pad = X * 128 // w
            sums.append(y2.sum(axis=-1).reshape(nb_pad, w // 128).sum(axis=-1))
        off += X * 128
    sums.append(jnp.zeros((total_len - n_pos,), dtype=y.dtype))
    return jnp.concatenate(sums)


class _Side(NamedTuple):
    """One blocked ELL side (source or destination).

    widths[b]: logical row width (pow2). xs[b]: physical lane-rows,
    multiple of 8. weight[b]: [X, 128] float64. slot_base[b]: first flat
    slot. pos_base[b]: first row-position in the side's position space
    (state order for the source side, z order for the destination side).
    row_nodes[b]: node id per logical row (length ≤ g·X; pad rows absent).
    row_pos[b]: position of each logical row — column-major in the
    [g, X] grid. edge_slot: flat slot per input edge. n_slots / n_pos:
    totals (pads included).
    """

    widths: tuple
    xs: tuple
    weight: list
    slot_base: tuple
    pos_base: tuple
    row_nodes: list
    row_pos: list
    edge_slot: np.ndarray
    n_slots: int
    n_pos: int


def _bucketize_blocked(n, key, other, weight, min_width=8):
    """Group edges by ``key`` node into blocked pow2-width ELL buckets."""
    order = stable_argsort_bounded(key, n)
    key_s = key[order].astype(np.int64)
    w_s = weight[order]

    deg = np.bincount(key_s, minlength=n).astype(np.int64)
    ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=ptr[1:])
    offset_in_row = np.arange(len(key_s), dtype=np.int64) - ptr[key_s]

    widths_per_row = np.maximum(
        min_width, 2 ** np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64)
    )
    widths_per_row[deg == 0] = 0
    used = tuple(sorted(int(w) for w in np.unique(widths_per_row) if w > 0))

    widths, xs, wmats, slot_bases, pos_bases = [], [], [], [], []
    row_nodes_l, row_pos_l = [], []
    edge_slot = np.empty(len(key_s), dtype=np.int64)
    slot_base = 0
    pos_base = 0
    for w in used:
        rows = np.nonzero(widths_per_row == w)[0]
        nb = len(rows)
        if w < 128:
            g = 128 // w                 # logical rows per lane-row
            X = -(-nb // g)              # lane-rows…
            X = -(-X // 8) * 8           # …padded to a multiple of 8
            n_pos_b = g * X              # padded grid positions
        else:
            X = nb * (w // 128)
            X = -(-X // 8) * 8
            # X stays divisible by w/128 (either w/128 ≤ 8 and X is a
            # multiple of 8, or nb·w/128 is already a multiple of 8)
            n_pos_b = X * 128 // w       # padded row count

        local = np.full(n, -1, dtype=np.int64)
        local[rows] = np.arange(nb)
        mask = widths_per_row[key_s] == w
        r = local[key_s[mask]]
        off = offset_in_row[mask]
        if w < 128:
            slot = (r // g) * 128 + (r % g) * w + off
            rpos = (np.arange(nb) % g) * X + np.arange(nb) // g
        else:
            slot = r * w + off           # [X, 128] row-major view
            rpos = np.arange(nb)

        wm = np.zeros(X * 128, dtype=np.float64)
        wm[slot] = w_s[mask]
        wmats.append(wm.reshape(X, 128))
        edge_slot[mask] = slot_base + slot

        widths.append(w)
        xs.append(X)
        slot_bases.append(slot_base)
        pos_bases.append(pos_base)
        row_nodes_l.append(rows)
        row_pos_l.append(pos_base + rpos)
        slot_base += X * 128
        pos_base += n_pos_b

    # undo the sort for edge_slot
    inv = np.empty_like(order)
    inv[order] = np.arange(len(order))
    return _Side(
        widths=tuple(widths),
        xs=tuple(xs),
        weight=wmats,
        slot_base=tuple(slot_bases),
        pos_base=tuple(pos_bases),
        row_nodes=row_nodes_l,
        row_pos=row_pos_l,
        edge_slot=edge_slot[inv],
        n_slots=slot_base,
        n_pos=pos_base,
    )


def save_operator_npz(op, path) -> None:
    """Field-driven npz serialization shared by the routed operators.

    Every dataclass field is stored under a named, type-tagged key
    (``int_*`` scalar, ``tup_*`` int tuple, ``arr_*`` array,
    ``lst_*_{i}`` list of arrays) — no positional meta vector to
    mis-index. The write is atomic (tmp + rename) so an interrupted run
    can never leave a truncated file under the final name."""
    import dataclasses
    import os

    payload = {"fmt_version": np.asarray(2, dtype=np.int64)}
    for f in dataclasses.fields(op):
        v = getattr(op, f.name)
        if v is None:
            continue  # optional field left unset: loaders default it
        if isinstance(v, (int, np.integer)):
            payload[f"int_{f.name}"] = np.asarray(v, dtype=np.int64)
        elif isinstance(v, tuple):
            payload[f"tup_{f.name}"] = np.asarray(v, dtype=np.int64)
        elif isinstance(v, np.ndarray):
            payload[f"arr_{f.name}"] = v
        elif isinstance(v, list):
            payload[f"cnt_{f.name}"] = np.asarray(len(v), dtype=np.int64)
            for i, a in enumerate(v):
                payload[f"lst_{f.name}_{i}"] = np.asarray(a)
        else:  # pragma: no cover - new field types need a tag here
            raise TypeError(f"unserializable field {f.name}: {type(v)}")
    path = str(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:  # file object: savez cannot append
            np.savez(fh, **payload)  # its own .npz suffix to the tmp name
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_operator_dir(op, path) -> None:
    """Directory twin of :func:`save_operator_npz`: one raw ``.npy``
    per array plus a ``meta.json``. No zip container means no CRC32
    pass and no chunked copies on load — at 10M peers (4 GB) the load
    drops from ~11 s (npz) to disk-stream speed (~3.5 s). Atomic via
    tmp-dir + rename."""
    import dataclasses
    import json
    import os
    import shutil

    path = str(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(tmp, exist_ok=True)
        meta = {"fmt_version": 3, "ints": {}, "tups": {}, "arrays": [],
                "lists": {}}
        for f in dataclasses.fields(op):
            v = getattr(op, f.name)
            if v is None:
                continue  # optional field left unset: loaders default it
            if isinstance(v, (int, np.integer)):
                meta["ints"][f.name] = int(v)
            elif isinstance(v, tuple):
                meta["tups"][f.name] = [int(x) for x in v]
            elif isinstance(v, np.ndarray):
                np.save(os.path.join(tmp, f"arr_{f.name}.npy"), v)
                meta["arrays"].append(f.name)
            elif isinstance(v, list):
                meta["lists"][f.name] = len(v)
                for i, a in enumerate(v):
                    np.save(os.path.join(tmp, f"lst_{f.name}_{i}.npy"),
                            np.asarray(a))
            else:  # pragma: no cover - new field types need a tag here
                raise TypeError(
                    f"unserializable field {f.name}: {type(v)}")
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta, fh)
        # swap the old cache out from under the final name, then swap
        # the new one in; if the final rename loses a race, restore the
        # old cache rather than leaking it
        old = f"{path}.old.{os.getpid()}"
        if os.path.isdir(path):
            os.rename(path, old)
        elif os.path.exists(path):
            os.unlink(path)
            old = None
        else:
            old = None
        try:
            os.rename(tmp, path)
        except OSError:
            if old is not None:
                if not os.path.exists(path):
                    try:
                        os.rename(old, path)  # previous cache back
                    except OSError:
                        pass  # surface the original failure below
                else:  # a concurrent writer won the race — drop ours
                    shutil.rmtree(old, ignore_errors=True)
            raise
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_operator_dir(cls, path, mmap: bool = True):
    """Inverse of :func:`save_operator_dir`.

    ``mmap=True`` (default) memory-maps every array: the operator is
    usable immediately and its ~4 GB (at 10M peers) page in exactly
    once, on demand, during device staging — instead of a full eager
    read (disk-bound, ~19 s cold) followed by a second pass in
    device_put. The maps are read-only; consumers that mutate must
    copy (none do)."""
    import dataclasses
    import json
    import os

    mode = "r" if mmap else None
    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in meta["ints"]:
            kwargs[f.name] = meta["ints"][f.name]
        elif f.name in meta["tups"]:
            kwargs[f.name] = tuple(meta["tups"][f.name])
        elif f.name in meta["arrays"]:
            kwargs[f.name] = np.load(
                os.path.join(path, f"arr_{f.name}.npy"), mmap_mode=mode)
        elif f.name in meta["lists"]:
            kwargs[f.name] = [
                np.load(os.path.join(path, f"lst_{f.name}_{i}.npy"),
                        mmap_mode=mode)
                for i in range(meta["lists"][f.name])
            ]
        elif f.default is not dataclasses.MISSING:
            kwargs[f.name] = f.default  # optional field, older cache
        else:
            raise ValueError(f"operator dir is missing field {f.name}")
    return cls(**kwargs)


def load_operator_npz(cls, z):
    """Inverse of :func:`save_operator_npz` for an open npz handle."""
    import dataclasses

    kwargs = {}
    for f in dataclasses.fields(cls):
        if f"int_{f.name}" in z:
            kwargs[f.name] = int(z[f"int_{f.name}"])
        elif f"tup_{f.name}" in z:
            kwargs[f.name] = tuple(int(x) for x in z[f"tup_{f.name}"])
        elif f"arr_{f.name}" in z:
            kwargs[f.name] = z[f"arr_{f.name}"]
        elif f"cnt_{f.name}" in z:
            kwargs[f.name] = [z[f"lst_{f.name}_{i}"]
                              for i in range(int(z[f"cnt_{f.name}"]))]
        elif f.default is not dataclasses.MISSING:
            kwargs[f.name] = f.default  # optional field, older cache
        else:
            raise ValueError(f"operator file is missing field {f.name}")
    return cls(**kwargs)


@dataclass
class RoutedOperator:
    """Host-side routed operator: blocked layouts, masks, route plans."""

    n: int
    n_valid: int
    nnz: int
    out_widths: tuple
    out_xs: tuple
    out_weight: list       # per bucket [X, 128] float64
    n_src_pos: int         # state slots occupied by source rows (pads incl.)
    state_to_node: np.ndarray  # state slot -> node id, -1 for dead slots
    in_widths: tuple
    in_xs: tuple
    in_n_pos: int
    edge_e: int
    edge_bits: tuple
    edge_stages: list
    state_e: int
    state_bits: tuple
    state_stages: list
    valid: np.ndarray      # [2^state_e] f32
    dangling: np.ndarray
    # flat out-side slot per FILTERED edge (the order filter_edges
    # returns — sorted by src*n+dst). The seam the incremental delta
    # engine patches through: slot -> (bucket, lane-row, lane) addresses
    # one value in the out_weight buffers. None on operators built (or
    # cached) before the delta engine existed; ensure_edge_slots
    # upgrades those in O(E) without a plan rebuild.
    out_edge_slot: np.ndarray | None = None
    # the bucket-width floor the build ran with — persisted because the
    # slot math is a function of it: ensure_edge_slots re-deriving
    # slots under a different min_width would scatter patches into the
    # wrong (row, lane) positions. Caches from before this field
    # load as 8 (the only default any cached operator was built with).
    min_width: int = 8

    @property
    def n_state(self) -> int:
        return 1 << self.state_e

    def initial_scores(self, initial: float, dtype=np.float32) -> np.ndarray:
        return _initial_scores(self.valid, initial, dtype)

    def scores_for_nodes(self, state_scores: np.ndarray) -> np.ndarray:
        """Translate a state-order score vector to node order."""
        return _scores_for_nodes(self.state_to_node, self.n, state_scores)

    def scores_from_nodes(self, node_scores: np.ndarray,
                          dtype=np.float32) -> np.ndarray:
        """Translate a node-order score vector to state order (warm start)."""
        return _scores_from_nodes(self.state_to_node, self.valid,
                                  node_scores, dtype)

    def save(self, path) -> None:
        """Persist the compiled operator so the one-time routing-plan
        compilation is reusable across runs. A path WITHOUT an ``.npz``
        suffix uses the raw-directory format (3× faster loads at 10M);
        ``.npz`` keeps the legacy container. Weights stay float64: the
        f64 converge path must round-trip losslessly."""
        if str(path).endswith(".npz"):
            save_operator_npz(self, path)
        else:
            save_operator_dir(self, path)

    @classmethod
    def load(cls, path) -> "RoutedOperator":
        import os

        if os.path.isdir(path):
            return load_operator_dir(cls, path)
        with np.load(path) as z:
            if "fmt_version" in z:
                return load_operator_npz(cls, z)
            # legacy v1 format (positional meta vector), kept readable so
            # pre-existing operator caches stay valid
            meta = z["meta"]
            out_widths = tuple(int(w) for w in z["out_widths"])
            return cls(
                n=int(meta[0]),
                n_valid=int(meta[1]),
                nnz=int(meta[2]),
                out_widths=out_widths,
                out_xs=tuple(int(x) for x in z["out_xs"]),
                out_weight=[z[f"out_weight_{i}"]
                            for i in range(len(out_widths))],
                n_src_pos=int(meta[3]),
                state_to_node=z["state_to_node"],
                in_widths=tuple(int(w) for w in z["in_widths"]),
                in_xs=tuple(int(x) for x in z["in_xs"]),
                in_n_pos=int(meta[6]),
                edge_e=int(meta[4]),
                edge_bits=tuple(int(b) for b in z["edge_bits"]),
                edge_stages=list(z["edge_stages"]),
                state_e=int(meta[5]),
                state_bits=tuple(int(b) for b in z["state_bits"]),
                state_stages=list(z["state_stages"]),
                valid=z["valid"],
                dangling=z["dangling"],
            )


def build_routed_operator(
    n, src, dst, val, valid=None, min_width: int = 8,
    prefer_native: bool = True,
) -> RoutedOperator:
    """Filter + normalize an edge list and compile the routing program.

    Semantics of ``filter_edges`` (the reference's opinion filter,
    ``dynamic_sets/native.rs:234-283``) are shared with the gather path.

    The build is the converge path's one-time compilation cost (minutes
    at 10M peers) — spanned and recorded as
    ``ptpu_routed_plan_build_seconds`` so operator-cache misses are
    attributable in the serve daemon's refresh latency.
    """
    from ..utils import trace as _trace

    # every full routing-plan compilation anywhere in the process —
    # the write-path cost the delta engine exists to amortize away; the
    # serve smoke asserts this stays FLAT under weight-revision churn
    _trace.counter("operator_full_builds").inc()
    with _trace.timed("routed_plan_build_seconds", "routed.plan_build",
                      n=n, edges=len(src)):
        op = _build_routed_operator(n, src, dst, val, valid, min_width,
                                    prefer_native)
    return op


def ensure_edge_slots(op: RoutedOperator, src, dst, weight) \
        -> RoutedOperator:
    """Upgrade a pre-delta-engine operator (cached without
    ``out_edge_slot``) in place: recompute the out-side bucketization —
    O(E) numpy, NO routing-plan rebuild — for the same filtered edge
    arrays the operator was built from. Deterministic: the slot math is
    the exact ``_bucketize_blocked`` pass the build ran, under the
    ``min_width`` the operator persists."""
    if op.out_edge_slot is None:
        op.out_edge_slot = _bucketize_blocked(
            n=op.n, key=np.asarray(src), other=np.asarray(dst),
            weight=np.asarray(weight), min_width=op.min_width).edge_slot
    return op


def _build_routed_operator(
    n, src, dst, val, valid, min_width: int, prefer_native: bool,
) -> RoutedOperator:
    src, dst, weight, valid_mask, dangling = filter_edges(n, src, dst, val, valid)

    # the two sides bucketize independently — overlap them on threads
    # (numpy's big sorts release the GIL), like the two plan builds
    # below; PTPU_PLAN_SERIAL=1 restores single-thread scheduling
    import os as _os
    from concurrent.futures import ThreadPoolExecutor

    if _os.environ.get("PTPU_PLAN_SERIAL", "0") != "1":
        with ThreadPoolExecutor(max_workers=2) as pool:
            out_f = pool.submit(_bucketize_blocked, n, src, dst, weight,
                                min_width)
            in_f = pool.submit(_bucketize_blocked, n, dst, src, weight,
                               min_width)
            out_side, in_side = out_f.result(), in_f.result()
    else:
        out_side = _bucketize_blocked(n, src, dst, weight, min_width)
        in_side = _bucketize_blocked(n, dst, src, weight, min_width)

    # state order: source-row positions first (column-major grids, dead
    # pad slots included), then out-edge-less nodes
    n_src_pos = out_side.n_pos
    src_pos = (np.concatenate(out_side.row_pos) if out_side.row_pos
               else np.zeros(0, dtype=np.int64))
    src_nodes = (np.concatenate(out_side.row_nodes) if out_side.row_nodes
                 else np.zeros(0, dtype=np.int64))
    has_out = np.zeros(n, dtype=bool)
    has_out[src_nodes] = True
    rest = np.nonzero(~has_out)[0]

    state_e = _ceil_pow2_exp(max(n_src_pos + len(rest), in_side.n_pos, 128))
    N2 = 1 << state_e
    state_to_node = np.full(N2, -1, dtype=np.int64)
    state_to_node[src_pos] = src_nodes
    state_to_node[n_src_pos : n_src_pos + len(rest)] = rest
    node_to_state = np.full(n, -1, dtype=np.int64)
    live = state_to_node >= 0
    node_to_state[state_to_node[live]] = np.nonzero(live)[0]

    # --- edge route: in slot <- out slot ---------------------------------
    # int32 throughout: these are 2^28-sized working arrays at 10M-peer
    # scale — int64 doubles their alloc + scatter traffic for slot ids
    # that fit 31 bits by construction (edge_e ≤ 31)
    edge_e = _ceil_pow2_exp(max(out_side.n_slots, in_side.n_slots, 128))
    E2 = 1 << edge_e
    assert edge_e <= 31, "edge slot space exceeds int32 (scale the " \
        "assembly dtypes before routing this graph)"
    perm = np.full(E2, -1, dtype=np.int32)
    perm[in_side.edge_slot] = out_side.edge_slot
    src_used = np.zeros(E2, dtype=bool)
    src_used[out_side.edge_slot] = True
    free_src = np.nonzero(~src_used)[0]   # out-ELL pads + tail: all zeros
    need = np.nonzero(perm < 0)[0]        # in-ELL pads + tail
    perm[need] = free_src[: len(need)]

    # --- state route: state slot <- z position ---------------------------
    # z = concatenated per-bucket in-row sums (column-major positions)
    in_nodes = (np.concatenate(in_side.row_nodes) if in_side.row_nodes
                else np.zeros(0, dtype=np.int64))
    in_pos = (np.concatenate(in_side.row_pos) if in_side.row_pos
              else np.zeros(0, dtype=np.int64))
    node_in_pos = np.full(n, -1, dtype=np.int64)
    node_in_pos[in_nodes] = in_pos
    assert state_e <= 31, "state slot space exceeds int32 (scale the " \
        "assembly dtypes before routing this graph)"
    sperm = np.full(N2, -1, dtype=np.int32)
    live_nodes = state_to_node[live]
    live_slots = np.nonzero(live)[0]
    with_in = node_in_pos[live_nodes] >= 0
    sperm[live_slots[with_in]] = node_in_pos[live_nodes[with_in]]
    sp_used = np.zeros(N2, dtype=bool)
    sp_used[sperm[sperm >= 0]] = True
    free_zero = np.nonzero(~sp_used)[0]   # z pads + tail: all zeros
    need = np.nonzero(sperm < 0)[0]
    sperm[need] = free_zero[: len(need)]
    # both plans at once: the state plan (2^state_e, typically 16x
    # smaller) rides in the edge plan's shadow — the threaded plan
    # build is the DEFAULT full-rebuild fast path
    plan, splan = plan_routes((perm, sperm), prefer_native=prefer_native)

    valid_state = np.zeros(N2, dtype=np.float32)
    valid_state[live_slots] = valid_mask[live_nodes].astype(np.float32)
    dangling_state = np.zeros(N2, dtype=np.float32)
    dangling_state[live_slots] = dangling[live_nodes].astype(np.float32)

    return RoutedOperator(
        n=n,
        n_valid=int(valid_mask.sum()),
        nnz=len(src),
        out_widths=out_side.widths,
        out_xs=out_side.xs,
        out_weight=out_side.weight,
        n_src_pos=n_src_pos,
        state_to_node=state_to_node,
        in_widths=in_side.widths,
        in_xs=in_side.xs,
        in_n_pos=in_side.n_pos,
        edge_e=plan.e,
        edge_bits=plan.bits,
        edge_stages=plan.stages,
        state_e=splan.e,
        state_bits=splan.bits,
        state_stages=splan.stages,
        valid=valid_state,
        dangling=dangling_state,
        out_edge_slot=out_side.edge_slot,
        min_width=min_width,
    )


class RoutedStatic(NamedTuple):
    """Hashable static config for the jitted routed kernels."""

    out_widths: tuple
    out_xs: tuple
    in_widths: tuple
    in_xs: tuple
    in_n_pos: int
    edge_e: int
    edge_bits: tuple
    state_e: int
    state_bits: tuple
    pallas: bool


def _expand_matrix(w: int, dtype) -> np.ndarray:
    """B[g, 128]: lane l takes grid row l // w."""
    g = 128 // w
    lanes = np.arange(128)
    return (lanes // w == np.arange(g)[:, None]).astype(dtype)


def routed_arrays(op: RoutedOperator, dtype=jnp.float32, alpha: float = 0.0,
                  pretrust=None, pallas: bool | None = None):
    """Device pytree + static config. ``alpha`` as in
    ``ops.converge.operator_arrays``. ``pretrust``, unlike the gather
    path's node-order vector, must be in **state order** with length
    ``2^state_e`` (zero on dead slots) — translate a node-order vector u
    via ``u[op.state_to_node] * (op.state_to_node >= 0)`` padded to
    ``op.n_state``; the default is uniform over valid peers."""
    if pallas is None:
        pallas = _use_pallas()
    if pretrust is None:
        pretrust = op.valid.astype(np.float64) / max(op.n_valid, 1)
    arrs = {
        "out_weight": tuple(jnp.asarray(w, dtype=dtype) for w in op.out_weight),
        "out_expand": tuple(
            jnp.asarray(_expand_matrix(w, np.float32), dtype=dtype)
            if w < 128 else None
            for w in op.out_widths),
        "in_reduce": tuple(
            jnp.asarray(_expand_matrix(w, np.float32), dtype=dtype)
            if w < 128 else None
            for w in op.in_widths),
        "edge_stages": tuple(jnp.asarray(s) for s in op.edge_stages),
        "state_stages": tuple(jnp.asarray(s) for s in op.state_stages),
        "valid": jnp.asarray(op.valid, dtype=dtype),
        "dangling": jnp.asarray(op.dangling, dtype=dtype),
        "n_valid": jnp.asarray(float(op.n_valid), dtype=dtype),
        "alpha": jnp.asarray(float(alpha), dtype=dtype),
        "pretrust": jnp.asarray(pretrust, dtype=dtype),
    }
    static = RoutedStatic(
        out_widths=op.out_widths,
        out_xs=op.out_xs,
        in_widths=op.in_widths,
        in_xs=op.in_xs,
        in_n_pos=op.in_n_pos,
        edge_e=op.edge_e,
        edge_bits=op.edge_bits,
        state_e=op.state_e,
        state_bits=op.state_bits,
        pallas=bool(pallas),
    )
    return arrs, static


_PREC = lax.Precision.HIGHEST


def spmv_routed(arrs: dict, static: RoutedStatic, s: jnp.ndarray) -> jnp.ndarray:
    """One application of the normalized trust operator (state order):
    broadcast → route → reduce → route-back → dangling + damping.

    Two optional keys turn this into the delta engine's PATCHED matvec
    (both branches are trace-time — present/absent splits the jit
    cache, never recompiles within a mode):

    - ``inv_row_scale`` ([2^state_e]): per-source-row normalization
      correction. The weight buffers store ``val / row_sum_at_build``;
      after in-place value patches the true row sum drifts, and scaling
      the *source score* by ``row_sum_at_build / row_sum_now`` restores
      exact normalization without rescattering O(out-degree) slots per
      revision.
    - ``tail_src``/``tail_dst``/``tail_w`` (fixed-capacity COO, state
      order): structural inserts applied since the last plan build —
      folded in with one scatter-add; unused capacity carries weight 0.
      The routing program itself never changes, so edge churn costs
      O(batch), not O(graph).
    """
    s_b = s * arrs["inv_row_scale"] if "inv_row_scale" in arrs else s
    x = blocked_broadcast(arrs, s_b, static.out_widths, static.out_xs,
                          1 << static.edge_e)
    y = _apply_route_jit(x, arrs["edge_stages"], static.edge_e,
                         static.edge_bits, static.pallas)
    z = blocked_reduce(arrs, y, static.in_widths, static.in_xs,
                       static.in_n_pos, 1 << static.state_e)
    base = _apply_route_jit(z, arrs["state_stages"], static.state_e,
                            static.state_bits, static.pallas)
    if "tail_w" in arrs:
        # tail weights are TRUE normalized weights (val / row_sum_now,
        # maintained host-side per batch) — no inv_row_scale here
        base = base + jnp.zeros_like(base).at[arrs["tail_dst"]].add(
            arrs["tail_w"] * s[arrs["tail_src"]])
    return dangling_and_damping(arrs, s, base)


@partial(jax.jit, static_argnames=("static", "num_iterations"))
def converge_routed_fixed(arrs, static: RoutedStatic, s0, num_iterations: int):
    """Reference-parity fixed-iteration power iteration, routed."""
    return lax.fori_loop(
        0, num_iterations, lambda _, s: spmv_routed(arrs, static, s), s0
    )


@partial(jax.jit, static_argnames=("static", "max_iterations", "accel_every"))
def converge_routed_adaptive(arrs, static: RoutedStatic, s0,
                             tol: float = 1e-6, max_iterations: int = 100,
                             accel_every: int = 0):
    """Iterate until the relative L1 delta ≤ tol (or max_iterations).
    ``accel_every`` enables the safeguarded extrapolation (see
    ``ops.converge.adaptive_loop``). Returns (scores, iterations_run,
    final_relative_delta)."""
    return adaptive_loop(
        lambda s: spmv_routed(arrs, static, s), s0, tol, max_iterations,
        accel_every,
    )


# --- generalized-semiring routed sweep -------------------------------------
#
# The Clos routes are pure permutations — semiring-agnostic by
# construction — so only the broadcast/reduce sides need algebra twins.
# Pad discipline carries over: every pad slot holds ``sr.zero`` (= 0.0
# for both shipped semirings — the max identity only because scores are
# nonnegative), so routed pads and free-slot fills stay correct under a
# max reduce exactly as they are under a sum.


def blocked_broadcast_semiring(arrs: dict, s, widths: tuple, xs: tuple,
                               total_len: int, sr: Semiring):
    """Semiring twin of :func:`blocked_broadcast`: expand a state
    vector into ``mul``-combined edge values. The 0/1 expansion einsum
    of the (+,×) path is really a lane-wise row SELECT — here it runs
    as an explicit repeat (w < 128: lane ``l`` takes grid row
    ``l // w``, the same layout ``_expand_matrix`` encodes) so ``mul``
    can be any binary op, not just multiply. Pad lanes carry weight 0
    → ``mul`` yields 0 on them (min of a nonnegative score with 0, or
    a product with 0)."""
    parts = []
    pos = 0
    for bi, (w, X) in enumerate(zip(widths, xs)):
        w_mat = arrs["out_weight"][bi]
        if w < 128:
            g = 128 // w
            s2t = lax.slice_in_dim(s, pos, pos + g * X).reshape(g, X)
            expanded = jnp.repeat(s2t.T, w, axis=1)   # [X, 128]
            v = sr.mul(expanded, w_mat)
            pos += g * X
        else:
            nb_pad = X * 128 // w
            rows = lax.slice_in_dim(s, pos, pos + nb_pad)
            expanded = jnp.broadcast_to(
                rows[:, None], (nb_pad, w // 128)).reshape(X, 1)
            v = sr.mul(jnp.broadcast_to(expanded, w_mat.shape), w_mat)
            pos += nb_pad
        parts.append(v.reshape(-1))
    used = sum(X * 128 for X in xs)
    parts.append(jnp.full((total_len - used,), sr.zero, dtype=s.dtype))
    return jnp.concatenate(parts)


def blocked_reduce_semiring(arrs: dict, y, widths: tuple, xs: tuple,
                            n_pos: int, total_len: int, sr: Semiring):
    """Semiring twin of :func:`blocked_reduce`: lane-segmented per-row
    ``reduce``. The w < 128 layout packs logical row ``r`` (lane-row
    ``x = r // g``, sub-row ``b = r % g``) into lanes
    ``[b·w, (b+1)·w)`` with z position ``b·X + x`` — so
    ``reshape(X, g, w) → reduce(-1) → transpose → flatten`` lands every
    row sum in exactly the slot the (+,×) einsum puts it in."""
    sums = []
    off = 0
    for bi, (w, X) in enumerate(zip(widths, xs)):
        y2 = lax.slice_in_dim(y, off, off + X * 128).reshape(X, 128)
        if w < 128:
            g = 128 // w
            z2 = sr.reduce(y2.reshape(X, g, w), axis=-1)   # [X, g]
            sums.append(z2.T.reshape(-1))
        else:
            nb_pad = X * 128 // w
            sums.append(sr.reduce(
                sr.reduce(y2, axis=-1).reshape(nb_pad, w // 128),
                axis=-1))
        off += X * 128
    sums.append(jnp.full((total_len - n_pos,), sr.zero, dtype=y.dtype))
    return jnp.concatenate(sums)


def spmv_routed_semiring(arrs: dict, static: RoutedStatic, s,
                         sr: Semiring):
    """One generalized sweep through the SAME compiled routed operator:
    broadcast → route → reduce → route-back under ``sr``, then the
    semiring tail. ``sr`` is static under jit, so the (+,×) branch
    compiles to exactly :func:`spmv_routed` and every other algebra
    reuses the operator's route plans untouched (routes are
    permutations — no algebra appears in them). The delta engine's
    patched-matvec keys (``inv_row_scale``/``tail_*``) are a (+,×)
    normalization concept and never reach this path."""
    if sr.name == "plusmul":
        return spmv_routed(arrs, static, s)
    x = blocked_broadcast_semiring(arrs, s, static.out_widths,
                                   static.out_xs, 1 << static.edge_e, sr)
    y = _apply_route_jit(x, arrs["edge_stages"], static.edge_e,
                         static.edge_bits, static.pallas)
    z = blocked_reduce_semiring(arrs, y, static.in_widths, static.in_xs,
                                static.in_n_pos, 1 << static.state_e, sr)
    base = _apply_route_jit(z, arrs["state_stages"], static.state_e,
                            static.state_bits, static.pallas)
    return semiring_tail(sr, arrs, s, base)


@partial(jax.jit, static_argnames=("static", "sr", "num_iterations"))
def converge_routed_fixed_semiring(arrs, static: RoutedStatic, s0,
                                   sr: Semiring, num_iterations: int):
    """Fixed-iteration routed power iteration under a pluggable
    semiring (static: one compile per algebra per operator shape)."""
    return lax.fori_loop(
        0, num_iterations,
        lambda _, s: spmv_routed_semiring(arrs, static, s, sr), s0)


@partial(jax.jit, static_argnames=("static", "sr", "max_iterations",
                                   "accel_every"))
def converge_routed_adaptive_semiring(arrs, static: RoutedStatic, s0,
                                      sr: Semiring, tol: float = 1e-6,
                                      max_iterations: int = 100,
                                      accel_every: int = 0):
    """Adaptive routed converge under a pluggable semiring. Returns
    (scores, iterations_run, final_relative_delta)."""
    return adaptive_loop(
        lambda s: spmv_routed_semiring(arrs, static, s, sr), s0, tol,
        max_iterations, accel_every)


@partial(jax.jit, static_argnames=("static", "sr", "max_iterations"))
def converge_routed_topics(arrs, static: RoutedStatic, s0k, sr: Semiring,
                           tol: float = 1e-6, max_iterations: int = 100):
    """Topic-batched adaptive converge through ONE routed operator:
    vmap K state-order topic vectors ``s0k[K, 2^state_e]`` over the
    compiled sweep — the routing-plan build (the path's one-time cost,
    ``ptpu_routed_plan_build_seconds``) is amortized across all K
    contexts. while_loop batching select-masks per-topic updates, so
    each topic's trajectory is independent of its batch neighbors.
    Returns ``(scores[K, ·], iters[K], delta[K])``."""
    return jax.vmap(
        lambda s0: adaptive_loop(
            lambda s: spmv_routed_semiring(arrs, static, s, sr), s0,
            tol, max_iterations))(s0k)
