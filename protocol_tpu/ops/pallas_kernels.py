"""Pallas TPU kernels for the batched field engine.

The jnp path in ``ops.fieldops`` expresses the Montgomery CIOS loop as
~22 separate XLA ops per step with materialized intermediates; this
module fuses the whole multiply into one Pallas kernel so the limb state
lives in registers/VMEM for all 22 steps.

Layout: limbs go on the sublane axis and the batch on the 128-wide lane
axis — a (L, 128) int32 tile per grid step — so every vector op in the
inner loop is a full-lane VPU op. The batch pads to a lane multiple;
padded rows compute garbage that is sliced off on the way out.

``pallas_mont_mul`` is a drop-in, bit-exact replacement for
``fieldops.mont_mul`` (property-tested against it and against Python
ints); ``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .fieldops import LIMB_BITS, MASK, NUM_LIMBS, FieldCtx

LANES = 128


def _mont_mul_kernel(p_inv_neg: int, x_ref, y_ref, p_ref, o_ref):
    """One (L, LANES) tile: CIOS Montgomery multiply along sublanes.

    Mirrors ``fieldops.mont_mul`` exactly: lazy limb accumulation
    (bounded < 2^30 in int32), exact low-limb quotient despite deferred
    carries, full carry ripple, one conditional subtract of p."""
    x = x_ref[...]  # (L, B)
    y = y_ref[...]
    p = p_ref[...]  # (L, B) — p limbs broadcast across lanes
    nb = x.shape[1]
    t = jnp.zeros((NUM_LIMBS + 2, nb), dtype=jnp.int32)

    def step(i, t):
        xi = lax.dynamic_slice_in_dim(x, i, 1, axis=0)  # (1, B)
        t = t.at[:NUM_LIMBS].add(xi * y)
        u = ((t[0] & MASK) * p_inv_neg) & MASK  # (B,)
        t = t.at[:NUM_LIMBS].add(u[None, :] * p)
        carry0 = t[0] >> LIMB_BITS
        t = jnp.concatenate(
            [t[1:], jnp.zeros((1, nb), dtype=jnp.int32)], axis=0)
        t = t.at[0].add(carry0)
        return t

    t = lax.fori_loop(0, NUM_LIMBS, step, t)

    def ripple(t):
        def pass_(_, t):
            carry = t >> LIMB_BITS
            shifted = jnp.concatenate(
                [jnp.zeros((1, nb), dtype=jnp.int32), carry[:-1]], axis=0)
            return (t & MASK) + shifted

        return lax.fori_loop(0, t.shape[0], pass_, t)

    t = ripple(t)[:NUM_LIMBS]

    # t >= p ? (top-down lexicographic, vectorized across lanes)
    gt = jnp.zeros((nb,), dtype=jnp.bool_)
    eq = jnp.ones((nb,), dtype=jnp.bool_)

    def cmp(j, state):
        gt, eq = state
        row = t[NUM_LIMBS - 1 - j]
        prow = p[NUM_LIMBS - 1 - j]
        gt = gt | (eq & (row > prow))
        eq = eq & (row == prow)
        return gt, eq

    gt, eq = lax.fori_loop(0, NUM_LIMBS, cmp, (gt, eq))
    sub = gt | eq
    t = ripple(t - jnp.where(sub[None, :], p, 0))
    o_ref[...] = t


@partial(jax.jit, static_argnames=("ctx", "interpret"))
def pallas_mont_mul(ctx: FieldCtx, x: jnp.ndarray, y: jnp.ndarray,
                    interpret: bool = False) -> jnp.ndarray:
    """Fused Montgomery product: same contract as ``fieldops.mont_mul``
    ((n, L) normalized rows in, (n, L) out, x may carry lazy sums < R).

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    tests); on TPU leave it False for the compiled kernel.
    """
    n = x.shape[0]
    n_pad = -(-n // LANES) * LANES
    xt = jnp.zeros((NUM_LIMBS, n_pad), dtype=jnp.int32)
    xt = xt.at[:, :n].set(x.T)
    yt = jnp.zeros((NUM_LIMBS, n_pad), dtype=jnp.int32)
    yt = yt.at[:, :n].set(y.T)
    p_tile = jnp.broadcast_to(
        jnp.asarray(ctx.p_limbs, dtype=jnp.int32)[:, None],
        (NUM_LIMBS, LANES),
    )

    grid = (n_pad // LANES,)
    out = pl.pallas_call(
        partial(_mont_mul_kernel, ctx.p_inv_neg),
        out_shape=jax.ShapeDtypeStruct((NUM_LIMBS, n_pad), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((NUM_LIMBS, LANES), lambda i: (0, i)),
            pl.BlockSpec((NUM_LIMBS, LANES), lambda i: (0, i)),
            pl.BlockSpec((NUM_LIMBS, LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((NUM_LIMBS, LANES), lambda i: (0, i)),
        interpret=interpret,
    )(xt, yt, p_tile)
    return out[:, :n].T
