"""Clos-network routing of static permutations — the TPU gather replacement.

XLA lowers a general 1-D gather on TPU to a scalar-unit loop (~7 ns per
element measured on v5e), which makes gather-SpMV the bottleneck of the
trust-graph power iteration at scale. But the SpMV's gather pattern is
*static* — fixed by the graph — and any static permutation can be routed
through a radix-128 Clos network whose stages are operations the TPU
vector unit executes at streaming bandwidth:

- **lane permutation**: ``out[row, j] = x[row, idx[row, j]]`` over
  ``[rows, 128]`` tiles — Mosaic's ``tpu.dynamic_gather`` along lanes,
  ~60 G elements/s on v5e (vs ~0.14 G for XLA's general gather);
- **transpose/reshape** between stages — XLA copies at HBM bandwidth.

A permutation of ``E = 128·m`` slots factors (König edge-coloring of the
128-regular bipartite row multigraph) into: an input lane permutation, a
perfect shuffle (transpose), 128 independent sub-permutations of size
``m`` (recursively routed, batched), the inverse shuffle, and an output
lane permutation. Depth is ``ceil(log2 E / 7)`` levels → ``2·levels − 1``
lane-perm stages: 7 stages route 2^28 slots (the 10M-peer edge array) in
~100 ms of streaming work instead of ~1.9 s of serial gather.

The plan (per-stage ``uint8`` lane-index arrays) is computed once per
graph on the host — ``native/protocol_native.cpp`` ``clos_plan`` in C++,
with a pure-Python twin here for small sizes and cross-validation. The
reference has no analogue of any of this (its matrix is 4×4,
``dynamic_sets/native.rs:319-329``); this is net-new TPU architecture
mandated by BASELINE.json's 10M-peer north star.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = [
    "RoutePlan",
    "plan_route",
    "plan_route_py",
    "plan_routes",
    "apply_route",
    "apply_route_np",
    "route_bits",
]


def route_bits(e: int) -> tuple:
    """Radix schedule for a 2^e-slot network: 7-bit (128-lane) levels with
    the remainder on the innermost (base) level."""
    if e <= 7:
        return (e,)
    nlev = -(-e // 7)
    rem = e - 7 * (nlev - 1)
    return (7,) * (nlev - 1) + (rem,)


@dataclass
class RoutePlan:
    """Routing program for ``y[d] = x[perm[d]]`` over ``E = 2^e`` slots.

    ``stages`` are flat uint8 arrays of length E in execution order
    (level-0 input, level-1 input, …, base, …, level-1 output, level-0
    output); ``stages[s][d]`` is the absolute lane (0..127) within slot
    d's 128-lane row that stage ``s`` reads from.
    """

    e: int
    bits: tuple
    stages: list

    @property
    def num_slots(self) -> int:
        return 1 << self.e


# --------------------------------------------------------------------------
# Planner (pure Python reference; the C++ twin lives in protocol_native)
# --------------------------------------------------------------------------


def _color_regular_bipartite(src_row, dst_row, m, r):
    """r-edge-color an r-regular bipartite multigraph given per-edge
    endpoints (both sides have ``m`` vertices). Recursive Euler halving:
    split a d-regular multigraph into two d/2-regular halves by
    2-coloring edges alternately along closed walks (every closed walk
    in a bipartite graph has even length, so the alternation pairs each
    vertex's incident edges), then recurse. Returns int32 color/edge."""
    E = len(src_row)
    colors = np.empty(E, dtype=np.int32)

    def split(eids, d, c0):
        if d == 1:
            colors[eids] = c0
            return
        k = len(eids)
        ls = src_row[eids]
        rs = dst_row[eids]
        lptr = np.zeros(m + 1, dtype=np.int64)
        rptr = np.zeros(m + 1, dtype=np.int64)
        np.cumsum(np.bincount(ls, minlength=m), out=lptr[1:])
        np.cumsum(np.bincount(rs, minlength=m), out=rptr[1:])
        ladj = np.argsort(ls, kind="stable")
        radj = np.argsort(rs, kind="stable")
        lcur = lptr[:-1].copy()
        rcur = rptr[:-1].copy()
        used = np.zeros(k, dtype=bool)
        side_a = np.zeros(k, dtype=bool)

        for start in range(k):
            if used[start]:
                continue
            v = int(ls[start])
            on_left = True
            parity = True
            while True:
                if on_left:
                    cur, ptr, adj = lcur, lptr, ladj
                else:
                    cur, ptr, adj = rcur, rptr, radj
                eid = -1
                while cur[v] < ptr[v + 1]:
                    cand = adj[cur[v]]
                    cur[v] += 1
                    if not used[cand]:
                        eid = int(cand)
                        break
                if eid < 0:
                    break  # closed walk complete (back at its start)
                used[eid] = True
                side_a[eid] = parity
                parity = not parity
                v = int(rs[eid]) if on_left else int(ls[eid])
                on_left = not on_left

        split(eids[side_a], d // 2, c0)
        split(eids[~side_a], d // 2, c0 + d // 2)

    split(np.arange(E, dtype=np.int64), r, 0)
    return colors


def plan_route_py(perm: np.ndarray) -> RoutePlan:
    """Pure-Python planner (small sizes, tests). ``perm`` must be a
    bijection on [0, 2^e), e ≥ 7; semantics y[d] = x[perm[d]]."""
    perm = np.asarray(perm, dtype=np.int64)
    E = len(perm)
    e = E.bit_length() - 1
    if (1 << e) != E or e < 7:
        raise ValueError("plan_route: length must be a power of two ≥ 128")
    bits = route_bits(e)
    nstages = 2 * len(bits) - 1
    stages = [np.zeros(E, dtype=np.uint8) for _ in range(nstages)]

    def rec(perm_l, slot_off, level):
        El = len(perm_l)
        if level == len(bits) - 1:
            # base: within-2^b-block permutation, absolute lane indices
            r = 1 << bits[level]
            sl = np.arange(El, dtype=np.int64) + slot_off
            block_base = (sl & 127) & ~(r - 1)
            stages[level][sl] = (block_base + perm_l).astype(np.uint8)
            return
        ml = El >> 7
        i_src = perm_l >> 7
        d_loc = np.arange(El, dtype=np.int64)
        i_dst = d_loc >> 7
        color = _color_regular_bipartite(i_src, i_dst, ml, 128)

        stages[level][slot_off + i_src * 128 + color] = (
            perm_l & 127
        ).astype(np.uint8)
        stages[nstages - 1 - level][slot_off + d_loc] = color.astype(np.uint8)

        mid = np.empty(El, dtype=np.int64)
        mid[color * ml + i_dst] = i_src
        for k in range(128):
            rec(mid[k * ml : (k + 1) * ml], slot_off + k * ml, level + 1)

    rec(perm.copy(), 0, 0)
    return RoutePlan(e=e, bits=bits, stages=stages)


def plan_route(perm: np.ndarray, prefer_native: bool = True,
               validate: bool = True) -> RoutePlan:
    """Plan a static permutation route; uses the C++ planner when built
    (required in practice beyond ~2^20 slots), Python otherwise.

    ``validate`` replays the finished plan on the host
    (``apply_route_np`` over ``arange(E)`` — seconds, vs minutes of
    planning at scale) and requires it to reproduce ``perm`` exactly: a
    consistent-but-wrong coloring would otherwise yield a non-bijective
    plan that silently corrupts every score it routes. On mismatch the
    native plan is discarded and the Python planner is tried once; if
    that also fails, raises.
    """
    import warnings

    perm = np.asarray(perm)
    E = len(perm)
    e = E.bit_length() - 1
    if (1 << e) != E or e < 7:
        raise ValueError("plan_route: length must be a power of two ≥ 128")

    native_plan_rejected = False

    def _check(plan, source):
        if not validate:
            return True
        probe = np.arange(E, dtype=np.int32 if e < 31 else np.int64)
        replay = None
        if e < 31:
            from .. import native as pn

            if pn.available():  # fused C++ replay (~5× the numpy one)
                replay = pn.clos_apply_route(plan.stages, plan.bits,
                                             probe)
        if replay is None:
            replay = apply_route_np(plan, probe)
        if np.array_equal(replay, perm):
            return True
        warnings.warn(
            f"plan_route: {source} planner produced a plan that does not "
            f"reproduce the permutation — discarding it",
            RuntimeWarning,
            stacklevel=3,
        )
        return False

    if prefer_native:
        from .. import native as pn

        if pn.available():
            bits = route_bits(e)
            stages_flat = pn.clos_plan(perm.astype(np.int32), bits)
            if stages_flat is not None:
                nstages = 2 * len(bits) - 1
                plan = RoutePlan(
                    e=e,
                    bits=bits,
                    stages=[stages_flat[s * E : (s + 1) * E]
                            for s in range(nstages)],
                )
                if _check(plan, "native"):
                    return plan
                native_plan_rejected = True
    if e > 20:
        reason = ("native planner produced an invalid plan (bug — please "
                  "report)" if native_plan_rejected
                  else "native planner unavailable")
        warnings.warn(
            f"plan_route: {reason}; falling back to the pure-Python "
            f"Euler-split planner, which visits every one of the 2^{e} "
            f"slots in Python — expect this to take a very long time",
            RuntimeWarning,
            stacklevel=2,
        )
    plan = plan_route_py(perm)
    if not _check(plan, "python"):
        raise RuntimeError(
            "plan_route: no planner produced a valid plan for this "
            "permutation"
        )
    return plan


def plan_routes(perms, prefer_native: bool = True,
                threads: bool | None = None) -> list:
    """Plan several independent permutations, overlapping their builds
    on host threads — the default full-rebuild fast path (VERDICT
    round-6 ask #8: the threaded plan build is no longer opt-in).

    The routed operator needs TWO plans per graph (the edge route and
    the much smaller state route); the native planner releases the GIL
    for the whole C++ walk and numpy releases it for the large sorts,
    so the state plan rides for free in the edge plan's shadow. Each
    native plan additionally fans its 128 level-0 sub-splits across the
    affinity CPU count by default (``CLOS_PLAN_THREADS`` overrides).
    ``threads=None`` → on, unless ``PTPU_PLAN_SERIAL=1`` (debug knob:
    deterministic single-thread scheduling for profiling)."""
    import os

    if threads is None:
        threads = os.environ.get("PTPU_PLAN_SERIAL", "0") != "1"
    perms = list(perms)
    if not threads or len(perms) <= 1:
        return [plan_route(p, prefer_native=prefer_native) for p in perms]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=len(perms)) as pool:
        futs = [pool.submit(plan_route, p, prefer_native)
                for p in perms]
        return [f.result() for f in futs]


# --------------------------------------------------------------------------
# Executor
# --------------------------------------------------------------------------


def apply_route_np(plan: RoutePlan, x: np.ndarray) -> np.ndarray:
    """Numpy twin of the device executor (planner validation)."""
    E = plan.num_slots
    bits = plan.bits
    x = np.asarray(x).reshape(E)
    si = 0
    for li in range(len(bits) - 1):
        B, m = 1 << (7 * li), E >> (7 * (li + 1))
        idx = plan.stages[si].reshape(-1, 128)
        x = np.take_along_axis(x.reshape(-1, 128), idx, axis=1)
        x = x.reshape(B, m, 128).swapaxes(1, 2).reshape(E)
        si += 1
    idx = plan.stages[si].reshape(-1, 128)
    x = np.take_along_axis(x.reshape(-1, 128), idx, axis=1).reshape(E)
    si += 1
    for li in reversed(range(len(bits) - 1)):
        B, m = 1 << (7 * li), E >> (7 * (li + 1))
        x = x.reshape(B, 128, m).swapaxes(1, 2).reshape(E)
        idx = plan.stages[si].reshape(-1, 128)
        x = np.take_along_axis(x.reshape(-1, 128), idx, axis=1).reshape(E)
        si += 1
    return x


def _lane_perm_pallas(x2d, idx2d):
    """One routing stage: per-row lane gather via tpu.dynamic_gather."""
    T, L = x2d.shape
    tile = min(1024, T)

    def kern(x_ref, i_ref, o_ref):
        o_ref[...] = jnp.take_along_axis(
            x_ref[...], i_ref[...].astype(jnp.int32), axis=1
        )

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((T, L), x2d.dtype),
        grid=(T // tile,),
        in_specs=[
            pl.BlockSpec((tile, L), lambda i: (i, 0)),
            pl.BlockSpec((tile, L), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile, L), lambda i: (i, 0)),
    )(x2d, idx2d)


def _use_pallas() -> bool:
    # the Mosaic lane-gather kernel is TPU-specific; every other backend
    # (CPU tests, GPU) takes the XLA take_along_axis fallback
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover — no backend at all
        return False


def _lane_perm(x, stage, pallas: bool):
    x2 = x.reshape(-1, 128)
    i2 = stage.reshape(-1, 128)
    # Mosaic tiles are 8 sublanes deep; tiny stages fall back to XLA
    if pallas and x2.shape[0] >= 8:
        return _lane_perm_pallas(x2, i2)
    return jnp.take_along_axis(x2, i2.astype(jnp.int32), axis=1)


def route_core(x, stages, si: int, e_sub: int, bits: tuple, pallas: bool):
    """Apply a route program to ``x`` of length B·2^e_sub — B independent
    subproblems batched contiguously (every reshape/transpose below works
    on El-sized chunks, so subproblem boundaries are never crossed). The
    sharded executor (parallel/routed.py) uses B > 1 for the device-local
    middle levels of a distributed route."""
    E = x.size
    for li in range(len(bits) - 1):
        El = 1 << (e_sub - 7 * li)
        B, m = E // El, El >> 7
        x = _lane_perm(x, stages[si], pallas)
        x = x.reshape(B, m, 128).swapaxes(1, 2).reshape(E)
        si += 1
    x = _lane_perm(x, stages[si], pallas).reshape(E)
    si += 1
    for li in reversed(range(len(bits) - 1)):
        El = 1 << (e_sub - 7 * li)
        B, m = E // El, El >> 7
        x = x.reshape(B, 128, m).swapaxes(1, 2).reshape(E)
        x = _lane_perm(x, stages[si], pallas).reshape(E)
        si += 1
    return x


@partial(jax.jit, static_argnames=("e", "bits", "pallas"))
def _apply_route_jit(x, stages, e, bits, pallas):
    return route_core(x, stages, 0, e, bits, pallas)


def apply_route(x, stages, e: int, bits: tuple, pallas: bool | None = None):
    """Route a device array through a plan: returns y with
    ``y[d] = x[perm[d]]``. ``stages`` is the tuple of flat uint8 device
    arrays from ``RoutePlan.stages``. Inside an outer jit, call
    ``_apply_route_jit`` directly with a concrete ``pallas`` flag."""
    if pallas is None:
        pallas = _use_pallas()
    return _apply_route_jit(x, tuple(stages), e, tuple(bits), pallas)
