"""The ConvergeBackend seam.

SURVEY.md §7 / BASELINE.json north star: cut a backend boundary at the
reference's ``EigenTrustSet::converge`` so the exact small-set semantics
(``backend=native``) and the TPU path (``backend=jax``) are interchangeable
consumers of the same filtered opinion data.

All backends consume the *filtered* opinion matrix (redistribution rows
already materialized by ``EigenTrustSet.filter_peers_ops`` — or, at scale,
the raw edge list which ``graph.filter_edges`` filters with identical
semantics) and return real-valued scores. The field-exact path stays on
``EigenTrustSet.converge`` itself — field scores are not float-approximable
(SURVEY.md §7.3) and are computed host-side or via ``ops.fieldops`` batched
field kernels for witnesses.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from fractions import Fraction
from typing import Sequence

import numpy as np


class ConvergeBackend(ABC):
    """Strategy interface for the real-valued convergence computation."""

    @abstractmethod
    def converge(
        self,
        matrix: Sequence[Sequence[float]],
        initial_score: float,
        num_iterations: int,
    ) -> np.ndarray:
        """Run the power iteration on a filtered opinion matrix."""


class NativeRationalBackend(ConvergeBackend):
    """Exact rational arithmetic — the correctness oracle
    (converge_rational, dynamic_sets/native.rs:340-392)."""

    def converge(self, matrix, initial_score, num_iterations):
        exact = self.converge_exact(matrix, initial_score, num_iterations)
        return np.array([float(x) for x in exact])

    def converge_exact(self, matrix, initial_score, num_iterations):
        """Same, returning the Fractions (for threshold decomposition).

        Float entries are lifted exactly via ``Fraction(v)`` (binary
        expansion). Like all backends, expects a *filtered* opinion matrix
        (zero row ⇔ empty slot that receives no trust).
        """
        n = len(matrix)
        norm = []
        for row in matrix:
            row_sum = sum(Fraction(v) for v in row) or Fraction(1)
            norm.append([Fraction(v) / row_sum for v in row])
        s = [Fraction(initial_score)] * n
        for _ in range(num_iterations):
            s = [sum(norm[j][i] * s[j] for j in range(n)) for i in range(n)]
        return s


class JaxDenseBackend(ConvergeBackend):
    """Dense device power iteration — MXU matvec per step. Right for
    fully-connected sets up to a few thousand peers."""

    def __init__(self, dtype=None):
        import jax.numpy as jnp

        self.dtype = dtype or jnp.float32

    def converge(self, matrix, initial_score, num_iterations):
        import jax.numpy as jnp

        from .graph import dense_normalized
        from .ops.converge import converge_dense_fixed

        m = np.asarray(matrix, dtype=np.float64)
        c = jnp.asarray(dense_normalized(m), dtype=self.dtype)
        has_row = m.sum(axis=1) > 0
        s0 = jnp.asarray(has_row, dtype=self.dtype) * float(initial_score)
        return np.asarray(converge_dense_fixed(c, s0, num_iterations))


class JaxSparseBackend(ConvergeBackend):
    """Bucketed-ELL gather-SpMV power iteration — the scale path.

    Accepts a dense filtered matrix (converted to edges) through the
    common interface; large graphs should use :meth:`converge_edges`
    directly with raw edge arrays.
    """

    def __init__(self, dtype=None):
        import jax.numpy as jnp

        self.dtype = dtype or jnp.float32

    def converge(self, matrix, initial_score, num_iterations):
        m = np.asarray(matrix, dtype=np.float64)
        src, dst = np.nonzero(m)
        # Contract: `matrix` is a *filtered* opinion matrix (zero row ⇔
        # empty slot). A zero-row peer that still receives trust would be
        # interpreted differently by the edge path (its in-edges dropped,
        # trusters renormalized) than by the dense/rational twins (mass
        # received then vanishing) — reject rather than silently diverge.
        valid = m.sum(axis=1) > 0
        receives = m.sum(axis=0) > 0
        bad = np.nonzero(~valid & receives)[0]
        if len(bad):
            raise ValueError(
                f"matrix is not filtered: zero-row peers {bad.tolist()} still "
                "receive trust; run it through EigenTrustSet.filter_peers_ops "
                "or use converge_edges with an explicit valid mask"
            )
        return self.converge_edges(
            m.shape[0], src, dst, m[src, dst], valid, initial_score, num_iterations
        )

    def converge_edges(
        self, n, src, dst, val, valid, initial_score, num_iterations, tol=None,
        alpha: float = 0.0, s0=None, semiring=None,
    ):
        """``s0`` (node-order, length n) warm-starts the power iteration —
        pair with :func:`ops.converge.warm_start_scores` to project a
        previous score vector onto the current peer set. Omitted, the
        cold uniform start (valid·initial_score) is used.

        ``semiring`` selects the sweep algebra (``ops.converge.SEMIRINGS``
        name or a ``Semiring``). The default — ``None`` / ``"plusmul"``
        — runs the PRE-EXISTING (+,×) kernels verbatim: same functions,
        same jit signatures, byte-identical iterate trajectory. Named
        variants (``"maxplus"`` bottleneck trust) run through the
        semiring twins over the same operator layouts."""
        import jax.numpy as jnp

        from .graph import build_operator
        from .ops.converge import (
            converge_sparse_adaptive,
            converge_sparse_adaptive_semiring,
            converge_sparse_fixed,
            converge_sparse_fixed_semiring,
            operator_arrays,
            resolve_semiring,
            timed_converge,
        )

        sr = resolve_semiring(semiring)
        op = build_operator(n, src, dst, val, valid)
        arrs = operator_arrays(op, dtype=self.dtype, alpha=alpha)
        if s0 is None:
            s0 = jnp.asarray(op.valid, dtype=self.dtype) * float(initial_score)
        else:
            s0 = jnp.asarray(np.asarray(s0), dtype=self.dtype)
        # the jit-cache identity of the converge call: bucket geometry +
        # dtype + static loop bound. A compile for a signature already
        # compiled once is a shape leak (steady-state recompile).
        sig = ("sparse", n, tuple(b.shape for b in op.bucket_idx),
               str(s0.dtype), "fixed" if tol is None else "adaptive",
               int(num_iterations))
        if sr.name != "plusmul":
            sig = sig + (sr.name,)
            if tol is None:
                scores = timed_converge(
                    "jax-sparse", n, len(src), sig,
                    lambda: converge_sparse_fixed_semiring(
                        arrs, s0, sr, num_iterations),
                    fixed_iterations=num_iterations, semiring=sr.name)
                return np.asarray(scores)
            scores, iters, delta = timed_converge(
                "jax-sparse", n, len(src), sig,
                lambda: converge_sparse_adaptive_semiring(
                    arrs, s0, sr, tol=tol, max_iterations=num_iterations),
                semiring=sr.name)
            return np.asarray(scores), int(iters), float(delta)
        if tol is None:
            scores = timed_converge(
                "jax-sparse", n, len(src), sig,
                lambda: converge_sparse_fixed(arrs, s0, num_iterations),
                fixed_iterations=num_iterations)
            return np.asarray(scores)
        scores, iters, delta = timed_converge(
            "jax-sparse", n, len(src), sig,
            lambda: converge_sparse_adaptive(
                arrs, s0, tol=tol, max_iterations=num_iterations))
        return np.asarray(scores), int(iters), float(delta)

    def converge_topics(
        self, n, src, dst, val, valid, s0_topics, max_iterations,
        tol=1e-6, alpha: float = 0.0, semiring=None,
    ):
        """Topic-batched adaptive converge: vmap the K node-order topic
        vectors ``s0_topics[K, n]`` through ONE operator (one build,
        one compiled sweep — the TrustFlow-style amortization). Each
        topic's trajectory is independent (while_loop batching
        select-masks converged topics). Returns
        ``(scores[K, n], iters[K], delta[K])`` as numpy."""
        import jax.numpy as jnp

        from .graph import build_operator
        from .ops.converge import (
            converge_sparse_topics,
            operator_arrays,
            resolve_semiring,
            timed_converge,
        )

        sr = resolve_semiring(semiring)
        s0k = np.asarray(s0_topics, dtype=np.float64)
        if s0k.ndim != 2 or s0k.shape[1] != n:
            raise ValueError(
                f"s0_topics must be [K, {n}] (got {s0k.shape})")
        op = build_operator(n, src, dst, val, valid)
        arrs = operator_arrays(op, dtype=self.dtype, alpha=alpha)
        s0k = jnp.asarray(s0k, dtype=self.dtype)
        sig = ("sparse-topics", n, int(s0k.shape[0]),
               tuple(b.shape for b in op.bucket_idx), str(s0k.dtype),
               int(max_iterations), sr.name)
        scores, iters, delta = timed_converge(
            "jax-sparse", n, len(src), sig,
            lambda: converge_sparse_topics(
                arrs, s0k, sr, tol=tol, max_iterations=max_iterations),
            semiring=sr.name)
        return (np.asarray(scores), np.asarray(iters),
                np.asarray(delta))


class JaxRoutedBackend(JaxSparseBackend):
    """Clos-routed SpMV power iteration (ops/routed.py) — the large-graph
    path: no general gathers; the sparse transpose runs as a permutation
    network of lane shuffles at streaming bandwidth. Same converge
    semantics as :class:`JaxSparseBackend`; pays a one-time host routing
    compilation per graph (reusable via ``RoutedOperator.save``)."""

    def converge_edges(
        self, n, src, dst, val, valid, initial_score, num_iterations, tol=None,
        alpha: float = 0.0, operator=None, s0=None, semiring=None,
    ):
        import jax.numpy as jnp

        from .ops.converge import resolve_semiring, timed_converge
        from .ops.routed import (
            build_routed_operator,
            converge_routed_adaptive,
            converge_routed_adaptive_semiring,
            converge_routed_fixed,
            converge_routed_fixed_semiring,
            routed_arrays,
        )

        sr = resolve_semiring(semiring)
        op = operator
        if op is None:
            op = build_routed_operator(n, src, dst, val, valid)
        arrs, static = routed_arrays(op, dtype=self.dtype, alpha=alpha)
        if s0 is None:
            s0 = jnp.asarray(op.initial_scores(initial_score,
                                               dtype=self.dtype))
        else:
            # node-order warm start → state-slot order
            s0 = jnp.asarray(op.scores_from_nodes(np.asarray(s0),
                                                  dtype=self.dtype))
        # the static tuple IS the routed jit cache key (hashable by
        # construction) — plus dtype and the static loop bound
        sig = ("routed", static, str(s0.dtype),
               "fixed" if tol is None else "adaptive", int(num_iterations))
        if sr.name != "plusmul":
            # the named-variant path: the SAME compiled route plans,
            # semiring twins for broadcast/reduce only
            sig = sig + (sr.name,)
            if tol is None:
                scores = timed_converge(
                    "jax-routed", n, int(op.nnz), sig,
                    lambda: converge_routed_fixed_semiring(
                        arrs, static, s0, sr, num_iterations),
                    fixed_iterations=num_iterations, semiring=sr.name)
                return op.scores_for_nodes(np.asarray(scores))
            scores, iters, delta = timed_converge(
                "jax-routed", n, int(op.nnz), sig,
                lambda: converge_routed_adaptive_semiring(
                    arrs, static, s0, sr, tol=tol,
                    max_iterations=num_iterations),
                semiring=sr.name)
            return (op.scores_for_nodes(np.asarray(scores)), int(iters),
                    float(delta))
        if tol is None:
            scores = timed_converge(
                "jax-routed", n, int(op.nnz), sig,
                lambda: converge_routed_fixed(arrs, static, s0,
                                              num_iterations),
                fixed_iterations=num_iterations)
            return op.scores_for_nodes(np.asarray(scores))
        scores, iters, delta = timed_converge(
            "jax-routed", n, int(op.nnz), sig,
            lambda: converge_routed_adaptive(
                arrs, static, s0, tol=tol, max_iterations=num_iterations))
        return (op.scores_for_nodes(np.asarray(scores)), int(iters),
                float(delta))

    def converge_topics(
        self, n, src, dst, val, valid, s0_topics, max_iterations,
        tol=1e-6, alpha: float = 0.0, operator=None, semiring=None,
    ):
        """Routed topic batch: K node-order topic vectors vmapped
        through ONE routed operator — exactly one routing-plan build
        (one ``ptpu_routed_plan_build_seconds`` sample) serves all K
        topics. Returns ``(scores[K, n], iters[K], delta[K])``."""
        import jax.numpy as jnp

        from .ops.converge import resolve_semiring, timed_converge
        from .ops.routed import (
            build_routed_operator,
            converge_routed_topics,
            routed_arrays,
        )

        sr = resolve_semiring(semiring)
        s0k = np.asarray(s0_topics, dtype=np.float64)
        if s0k.ndim != 2 or s0k.shape[1] != n:
            raise ValueError(
                f"s0_topics must be [K, {n}] (got {s0k.shape})")
        op = operator
        if op is None:
            op = build_routed_operator(n, src, dst, val, valid)
        arrs, static = routed_arrays(op, dtype=self.dtype, alpha=alpha)
        s0k = jnp.asarray(
            np.stack([op.scores_from_nodes(row, dtype=self.dtype)
                      for row in s0k]))
        sig = ("routed-topics", static, int(s0k.shape[0]),
               str(s0k.dtype), int(max_iterations), sr.name)
        scores, iters, delta = timed_converge(
            "jax-routed", n, int(op.nnz), sig,
            lambda: converge_routed_topics(
                arrs, static, s0k, sr, tol=tol,
                max_iterations=max_iterations),
            semiring=sr.name)
        return (np.stack([op.scores_for_nodes(row)
                          for row in np.asarray(scores)]),
                np.asarray(iters), np.asarray(delta))
