"""Headline benchmark: large-peer trust-graph convergence on TPU.

BASELINE.json north star: converge a 10M-peer power-law trust graph to a
1e-6 relative-L1 delta in under 5 s wall-clock. The reference publishes no
numbers (BASELINE.md) — the 5 s target is the baseline this framework is
judged against, so ``vs_baseline`` = target_seconds / measured_seconds
(>1 means faster than target).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Backends: ``routed`` (default at scale) runs the Clos-routed SpMV
(ops/routed.py) — no general gathers, the sparse transpose executes as a
permutation network of lane shuffles; ``gather`` runs the bucketed-ELL
gather SpMV (ops/converge.py). The routing plan is compiled once per
graph on the host (C++ planner) and cached under ``--cache-dir`` so
repeat runs skip straight to the device phase.

Methodology: graph build, operator packing/plan compilation (host, numpy/
C++) and jit compile are excluded; the timed region is the adaptive
converge call's device compute, synced by fetching the scalar convergence
delta (over tunneled transports ``block_until_ready`` can return early,
and fetching the full score vector would time the tunnel's transfer
bandwidth, not the kernel). Median of 3.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np


def routed_cache_path(cache_dir, n: int, m: int) -> Path:
    """The routed-operator plan cache key — ONE definition: the main
    bench path and the churn ladder must load the same cached plan for
    the same arguments."""
    return Path(cache_dir) / f"routed_ba_n{n}_m{m}_s0_v2"


def _fmt_peers(n: int) -> str:
    if n >= 1_000_000 and n % 1_000_000 == 0:
        return f"{n // 1_000_000}M"
    if n >= 1_000 and n % 1_000 == 0:
        return f"{n // 1_000}K"
    return str(n)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--ingest", action="store_true",
                        help="measure the batched attestation-ingest "
                             "kernels instead of converge (delegates to "
                             "tools/bench_ingest.py; --n = attestations)")
    parser.add_argument("--n", type=int, default=10_000_000, help="peers")
    parser.add_argument("--m", type=int, default=8, help="BA attachment degree")
    parser.add_argument("--tol", type=float, default=1e-6)
    parser.add_argument("--alpha", type=float, default=0.1)
    parser.add_argument("--max-iters", type=int, default=500)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--backend", choices=["auto", "routed", "gather"],
                        default="auto")
    parser.add_argument("--cache-dir", default="bench_cache",
                        help="routed-operator cache ('' disables)")
    parser.add_argument("--churn", action="store_true",
                        help="measure the steady-state edge-churn cost "
                             "(delta-apply per batch through "
                             "protocol_tpu.incremental) against the "
                             "full routing-plan build it replaces")
    parser.add_argument("--churn-batches", type=int, default=20)
    parser.add_argument("--churn-edges", type=int, default=500,
                        help="weight revisions per churn batch")
    parser.add_argument("--churn-frontiers", default="",
                        help="comma-separated target frontier scales: "
                             "switches --churn to the sublinear-refresh"
                             " ladder bench (BENCH_r09) — sustained "
                             "localized churn at each scale, device-"
                             "partial/sampled refresh vs the full-"
                             "sweep fallback, L1 error vs the declared "
                             "budget, zero operator builds")
    parser.add_argument("--churn-factors", default="0.002,0.2,0.2",
                        help="comma-separated relative weight-revision "
                             "magnitudes, one per --churn-frontiers "
                             "scale (cycled if shorter): a gentle "
                             "first scale keeps influence local "
                             "(device_partial rung), strong ones "
                             "flood (sampled rung)")
    parser.add_argument("--frontier-limit-fraction", type=float,
                        default=0.25,
                        help="partial-bound fraction of n for the "
                             "ladder bench (mirrors "
                             "partial_frontier_fraction)")
    parser.add_argument("--sample-budget", type=int, default=2_000_000,
                        help="sampled-mode row budget for the ladder "
                             "bench")
    parser.add_argument("--error-budget", type=float, default=1e-3,
                        help="declared relative-L1 error budget of the "
                             "sublinear rungs (mirrors "
                             "refresh_error_budget); actual spend is "
                             "asserted under it and the L1 error vs "
                             "the oracle under the spend")
    parser.add_argument("--msm", action="store_true",
                        help="measure the batched multi-column commit "
                             "MSM (native.g1_msm_multi) against K "
                             "serial g1_msm calls: the K-column "
                             "aggregate-speedup curve the commit "
                             "engine rides, bit-exact per column")
    parser.add_argument("--msm-sizes", default="18,19,20",
                        help="comma-separated log2 point counts")
    parser.add_argument("--msm-cols", default="1,2,4,8",
                        help="comma-separated K values")
    parser.add_argument("--msm-reps", type=int, default=2,
                        help="repetitions per (n, K) cell (best-of)")
    parser.add_argument("--proofs", action="store_true",
                        help="measure proof-pool throughput: concurrent "
                             "clients against the ProofWorkerPool at "
                             "each worker count (proofs/hour scaling "
                             "curve, affinity hit rate, shed counters, "
                             "byte parity with the single-worker path)")
    parser.add_argument("--proof-jobs", type=int, default=16,
                        help="proofs per worker-count measurement")
    parser.add_argument("--proof-k", type=int, default=8,
                        help="synthetic circuit domain exponent")
    parser.add_argument("--proof-gates", type=int, default=48)
    parser.add_argument("--clients", type=int, default=4,
                        help="concurrent submitting clients")
    parser.add_argument("--workers-list", default="1,2",
                        help="comma-separated worker counts to sweep")
    parser.add_argument("--sharded", action="store_true",
                        help="BENCH_r10: intra-prove sharding — one "
                             "flagship-shape prove's wall clock at "
                             "1/2/4 workers (worker lending) under the "
                             "device-window methodology, plus real-"
                             "prove byte parity through the pool")
    parser.add_argument("--shard-k", type=int, default=16,
                        help="log2 column length of the flagship-shape "
                             "commit flush")
    parser.add_argument("--shard-cols", type=int, default=8,
                        help="commit columns per flagship-shape flush")
    parser.add_argument("--shard-workers", default="1,2,4",
                        help="worker counts for the sharded curve")
    parser.add_argument("--shard-reps", type=int, default=3,
                        help="best-of-N per cell")
    parser.add_argument("--shard-window", type=float, default=0.0,
                        help="device-occupancy window seconds inside "
                             "the flagship-shape prove (0 = auto: the "
                             "measured inline commit wall, the "
                             "flagship regime where device quotient "
                             "and commit wall are comparable)")
    parser.add_argument("--fabric", action="store_true",
                        help="BENCH_r13: cross-process proving fabric "
                             "— one flagship-shape prove's wall clock "
                             "vs EXTERNAL prove-worker process count "
                             "(units serialized through a FabricStore, "
                             "executed by real OS processes), "
                             "transcript digest asserted equal to the "
                             "inline flush at every cell")
    parser.add_argument("--fabric-workers", default="0,1,2,4",
                        help="comma-separated external worker process "
                             "counts for the fabric curve")
    parser.add_argument("--fabric-reps", type=int, default=3,
                        help="best-of-N per fabric cell")
    parser.add_argument("--reads", action="store_true",
                        help="BENCH_r11: read-path scale-out — read "
                             "QPS vs follower-replica count under "
                             "concurrent churn ingest on the leader, "
                             "p95 replication lag, leader refresh "
                             "interference, byte-equality asserted at "
                             "the same WAL position (real CLI daemons "
                             "over the mock devnet)")
    parser.add_argument("--read-followers", default="0,1,2",
                        help="comma-separated follower counts to sweep "
                             "(0 = leader-only baseline)")
    parser.add_argument("--read-seconds", type=float, default=8.0,
                        help="measurement window per cell")
    parser.add_argument("--read-clients", type=int, default=4,
                        help="concurrent read clients")
    parser.add_argument("--churn-rate", type=float, default=3.0,
                        help="attestations/second posted to the "
                             "leader during every measurement window")
    parser.add_argument("--device-window", type=float, default=1.2,
                        help="per-proof device-occupancy window in "
                             "seconds (GIL-released wait modeling the "
                             "device-resident phase of a real prove; "
                             "see bench_proofs docstring). 0 disables")
    parser.add_argument("--scenario", action="store_true",
                        help="BENCH_r12: adversarial robustness matrix "
                             "— every {topology x semiring x scale} "
                             "cell through protocol_tpu.scenarios "
                             "(attacker mass capture, honest rank "
                             "displacement, iterations vs the damped "
                             "bound) — plus the topic-batch "
                             "amortization headline: K topic vectors "
                             "vmapped through ONE routed operator vs K "
                             "sequential converges each paying its own "
                             "plan build")
    parser.add_argument("--scenario-peers", default="10000,100000,1000000",
                        help="comma-separated scale sweep for the "
                             "robustness matrix")
    parser.add_argument("--scenario-topologies",
                        default="sybil-ring,collusion,slander",
                        help="comma-separated attack families")
    parser.add_argument("--scenario-seed", type=int, default=7)
    parser.add_argument("--scenario-topics", type=int, default=8,
                        help="K for the topic-batch amortization cell")
    parser.add_argument("--scenario-topic-peers", type=int, default=20_000,
                        help="graph size for the topic-batch cell "
                             "(routed engine: the plan build being "
                             "amortized must be non-trivial)")
    args = parser.parse_args()

    if args.scenario:
        return bench_scenario(args)

    if args.msm:
        return bench_msm(args)

    if args.reads:
        return bench_reads(args)

    if args.proofs:
        return bench_proofs(args)

    if args.sharded:
        return bench_sharded(args)

    if args.fabric:
        return bench_fabric(args)

    if args.ingest:
        # chip-measured att/s for hash + binding-checked GLV recovery;
        # 32k chunks ride far under the bisected ~408k worker-crash
        # lane ceiling (tools/probe_lane_crash.py canary).
        # NOTE: no local `import subprocess` here — a local import
        # shadows the module-level one for the WHOLE function, making
        # the non-ingest probe-and-retry path below die with
        # UnboundLocalError (exactly how the r5 battery's bench step
        # failed).
        n_att = args.n if args.n != 10_000_000 else 1 << 20
        return subprocess.call(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools", "bench_ingest.py"),
             "--n", str(n_att), "--chunk", "32768"])

    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    # the tunnel backend has failed init transiently after heavy prior
    # sessions (r5 outage note in BASELINE.md); one bounded PRE-import
    # probe-and-retry saves the round's bench row when recovery is near
    # without stalling the driver indefinitely. The probe runs in a
    # subprocess because jax caches a failed backend init for the
    # process lifetime (PTPU_BENCH_INIT_RETRIES=0 disables).
    retries = int(os.environ.get("PTPU_BENCH_INIT_RETRIES", "1"))
    if "axon" not in os.environ.get("JAX_PLATFORMS", ""):
        retries = 0  # CPU/local backends don't have the tunnel hazard
    for attempt in range(retries):
        try:
            probe_rc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, text=True,
                timeout=300).returncode
        except subprocess.TimeoutExpired:
            probe_rc = -1  # a HUNG init counts as a failed probe
        if probe_rc == 0:
            break
        print("bench: backend init probe failed; retrying in 240s",
              file=sys.stderr, flush=True)
        time.sleep(240)

    import jax
    import jax.numpy as jnp

    from protocol_tpu.graph import barabasi_albert_edges, build_operator
    from protocol_tpu.ops.converge import converge_sparse_adaptive, operator_arrays
    from protocol_tpu.ops.routed import (
        RoutedOperator,
        build_routed_operator,
        converge_routed_adaptive,
        routed_arrays,
    )

    backend = args.backend
    if backend == "auto":
        # the routed path wins beyond ~100K peers; below that the plan
        # compilation outweighs the per-iteration gather savings
        backend = "routed" if args.n >= 100_000 else "gather"
    if backend == "routed":
        # the pure-Python planner fallback is per-edge host work —
        # without the native planner, large routed builds take hours
        from protocol_tpu import native as pn

        if not pn.available():
            print("bench: native Clos planner unavailable; "
                  "falling back to gather backend", file=sys.stderr)
            backend = "gather"

    if args.churn:
        if args.churn_frontiers:
            return bench_refresh_ladder(args)
        return bench_churn(args)

    t0 = time.perf_counter()
    rop = None
    cache_path = None
    if backend == "routed" and args.cache_dir:
        # raw-directory cache (fast loads); migrate a legacy .npz once
        cache_path = routed_cache_path(args.cache_dir, args.n, args.m)
        legacy = (Path(args.cache_dir)
                  / f"routed_ba_n{args.n}_m{args.m}_s0_v1.npz")
        if cache_path.exists():
            rop = RoutedOperator.load(cache_path)
        elif legacy.exists():
            rop = RoutedOperator.load(legacy)
            rop.save(cache_path)
            # migration complete — don't double the cache (idempotent
            # for concurrent runs)
            legacy.unlink(missing_ok=True)

    if backend == "routed":
        if rop is None:
            src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
            rop = build_routed_operator(args.n, src, dst, val)
            if cache_path is not None:
                cache_path.parent.mkdir(parents=True, exist_ok=True)
                rop.save(cache_path)
        build_s = time.perf_counter() - t0
        arrs, static = routed_arrays(rop, dtype=jnp.float32, alpha=args.alpha)
        arrs = jax.device_put(arrs)
        s0 = jax.device_put(jnp.asarray(rop.initial_scores(1000.0)))
        n_valid = rop.n_valid
        nnz = rop.nnz

        def run():
            return converge_routed_adaptive(
                arrs, static, s0, tol=args.tol, max_iterations=args.max_iters
            )

        def final_total(scores):
            return float(rop.scores_for_nodes(np.asarray(scores)).sum())
    else:
        src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
        op = build_operator(args.n, src, dst, val)
        build_s = time.perf_counter() - t0
        arrs = jax.device_put(operator_arrays(op, dtype=jnp.float32,
                                              alpha=args.alpha))
        s0 = jax.device_put(jnp.asarray(op.valid, dtype=jnp.float32) * 1000.0)
        n_valid = op.n_valid
        nnz = int(sum(int((b != 0).sum()) for b in op.bucket_val))

        def run():
            return converge_sparse_adaptive(
                arrs, s0, tol=args.tol, max_iterations=args.max_iters
            )

        def final_total(scores):
            return float(np.asarray(scores).sum())

    # compile outside the timed region; sync via a host transfer of the
    # scalar delta (over tunneled TPU transports, block_until_ready can
    # return before execution finishes)
    scores, iters, delta = run()
    float(delta)

    times = []
    for _ in range(args.repeats):
        t1 = time.perf_counter()
        scores, iters, delta = run()
        float(delta)
        times.append(time.perf_counter() - t1)
    wall = float(np.median(times))

    total = final_total(scores)
    expected = n_valid * 1000.0
    meta = {
        "backend": backend,
        "n_peers": args.n,
        "edges": nnz,
        "iterations": int(iters),
        "final_delta": float(delta),
        "converged": bool(float(delta) <= args.tol),
        "conservation_rel_err": abs(total - expected) / expected,
        "build_s": round(build_s, 1),
        "device": str(jax.devices()[0]),
        "times_s": [round(t, 4) for t in times],
    }
    print(json.dumps(meta), file=sys.stderr)

    target_s = 5.0
    print(
        json.dumps(
            {
                "metric": f"{_fmt_peers(args.n)}-peer trust convergence to "
                f"{args.tol:.0e} L1 delta, wall-clock",
                "value": round(wall, 4),
                "unit": "s",
                "vs_baseline": round(target_s / wall, 3),
            }
        )
    )
    # a wall-clock for a run that never hit the advertised tolerance is not
    # a valid headline number — fail loudly (meta on stderr has the delta)
    if not meta["converged"]:
        print("BENCH FAILED: did not converge to tolerance", file=sys.stderr)
        return 1
    return 0


def bench_scenario(args) -> int:
    """BENCH_r12: the adversarial robustness matrix + topic batching.

    Part 1 — robustness matrix: every {topology × semiring × scale}
    cell runs through ``protocol_tpu.scenarios.run_scenario`` (same
    code path as the ``scenario`` CLI verb), recording attacker
    score-mass capture, honest rank displacement vs the attack-free
    baseline, and measured iterations vs the damped-bound prediction.
    Cells stream to stderr as JSON; the matrix lands in the meta.

    Part 2 — the headline: topic-batch amortization. K topic score
    vectors vmapped through ONE routed operator (one routing-plan
    build, one compiled sweep) against K sequential converges each
    paying its own plan build — the TrustFlow-style amortization the
    semiring seam's ``converge_topics`` exists for. Results are
    asserted equal before timing counts.
    """
    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    import numpy as np

    from protocol_tpu.backend import JaxRoutedBackend
    from protocol_tpu.graph import barabasi_albert_edges
    from protocol_tpu.scenarios import run_scenario

    topologies = [t for t in args.scenario_topologies.split(",") if t]
    scales = [int(s) for s in args.scenario_peers.split(",") if s]
    matrix = []
    for topo in topologies:
        for semiring in ("plusmul", "maxplus"):
            for peers in scales:
                r = run_scenario(topo, peers=peers, semiring=semiring,
                                 seed=args.scenario_seed, alpha=0.1,
                                 timing=True)
                rob = r["robustness"]
                cell = {
                    "topology": topo,
                    "semiring": semiring,
                    "peers": peers,
                    "edges": r["edges"],
                    "engine": r["engine"],
                    "attacker_mass_capture":
                        round(rob["attacker_mass_capture"], 6),
                    "baseline_attacker_mass":
                        round(rob["baseline_attacker_mass"], 6),
                    "rank_disp_mean":
                        round(rob["honest_rank_displacement"]["mean"], 3),
                    "attackers_in_top100":
                        rob["attackers_in_top"]["count"],
                    "iterations": rob["iterations"],
                    "iteration_bound": rob["iteration_bound"],
                    "within_bound": rob["within_bound"],
                    "converge_s": round(r["timing_s"]["attack_converge"], 3),
                }
                print(json.dumps(cell), file=sys.stderr, flush=True)
                matrix.append(cell)

    # --- part 2: topic-batch amortization --------------------------------
    n, m, K = args.scenario_topic_peers, 4, args.scenario_topics
    src, dst, val = barabasi_albert_edges(n, m, seed=args.scenario_seed)
    valid = np.ones(n, dtype=bool)
    rng = np.random.default_rng(args.scenario_seed)
    s0k = rng.uniform(0.5, 1.5, size=(K, n)) * 1000.0
    tol, max_iters = 1e-6, 200

    t0 = time.perf_counter()
    seq_scores = []
    for k in range(K):
        # a FRESH backend per topic: each sequential converge pays its
        # own routing-plan build, which is exactly the cost the batched
        # path amortizes
        sk, _, _ = JaxRoutedBackend().converge_edges(
            n, src, dst, val, valid, 1000.0, max_iters, tol=tol,
            alpha=0.1, s0=s0k[k])
        seq_scores.append(np.asarray(sk))
    seq_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    batch_scores, batch_iters, _ = JaxRoutedBackend().converge_topics(
        n, src, dst, val, valid, s0k, max_iters, tol=tol, alpha=0.1)
    batch_s = time.perf_counter() - t0

    err = float(np.max(np.abs(np.stack(seq_scores) - batch_scores)))
    rel = err / 1000.0
    if rel > 1e-5:
        print(f"BENCH FAILED: topic-batch scores diverge from the "
              f"sequential oracle (rel {rel:.2e})", file=sys.stderr)
        return 1
    speedup = seq_s / batch_s if batch_s > 0 else float("inf")

    # the honesty split: what the batch actually amortizes is the
    # routing-plan build (K host builds -> 1), so the total-wall
    # speedup is capped at 1 + build/converge on THIS box. On CPU the
    # sweep dominates and the cap sits near 1.15x; at 10M peers the
    # plan build is minutes (see `sparse-scores --operator-cache`)
    # while a sweep is not, and the same code path approaches Kx.
    from protocol_tpu.ops.routed import build_routed_operator

    t0 = time.perf_counter()
    build_routed_operator(n, src, dst, val, valid)
    build_s = time.perf_counter() - t0
    per_converge = max(seq_s / K - build_s, 1e-9)
    ceiling = 1.0 + build_s / per_converge

    meta = {
        "matrix": matrix,
        "seed": args.scenario_seed,
        "topic_batch": {
            "peers": n, "topics": K,
            "sequential_s": round(seq_s, 3),
            "batched_s": round(batch_s, 3),
            "speedup": round(speedup, 2),
            "plan_builds": {"sequential": K, "batched": 1},
            "plan_build_s": round(build_s, 3),
            "amortization_ceiling_x": round(ceiling, 2),
            "max_rel_err": rel,
            "iters": [int(i) for i in np.asarray(batch_iters)],
        },
        "note": "matrix cells are deterministic per seed (the scenario "
                "runner's reproducibility contract); the topic-batch "
                "headline is K topic vectors through ONE routed "
                "operator build vs K sequential converges each paying "
                "its own build — the batch eliminates K-1 plan builds "
                "outright, so the wall speedup tracks the build/sweep "
                "ratio (amortization_ceiling_x on this box; build is "
                "minutes at 10M peers where the same path nears Kx)",
    }
    print(json.dumps(meta), file=sys.stderr)
    print(json.dumps({
        "metric": f"topic-batch amortization: {K} topic converges, "
                  f"routing-plan builds {K}->1, at {_fmt_peers(n)} "
                  f"peers (wall ceiling {ceiling:.2f}x on this box; "
                  f"robustness matrix: {len(matrix)} cells, all within "
                  f"the damped bound: "
                  f"{all(c['within_bound'] for c in matrix)})",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 2),
    }))
    return 0


def bench_msm(args) -> int:
    """K-column commit-MSM batching: ``native.g1_msm_multi`` (the
    commit engine's kernel — base parse/Montgomery conversion amortized
    over all K columns, on-the-fly signed recode, bucket-range-tiled
    batch-affine levels, 32-chain IFMA bucket reduction) against K
    serial ``native.g1_msm`` calls (the
    committed-baseline Pippenger, BASELINE.md r4's 3.9 s at 2^20 —
    kept untouched as the oracle). Single-threaded, same box, same
    ``PN_MSM_C``/auto-tune state for both sides; every column is
    asserted bit-exact against its serial oracle before timing counts.

    Headline ``value`` = aggregate speedup at the largest size's K=4
    cell (serial wall / multi wall); ``vs_baseline`` = value / 1.5,
    the BENCH_r08 acceptance floor (>1 means the batching beat it)."""
    import random

    from protocol_tpu import native
    from protocol_tpu.utils.fields import BN254_FR_MODULUS as FR
    from protocol_tpu.zk.bn254 import BN254_FQ_MODULUS as FQ, G1_GEN

    if not native.available():
        print("BENCH FAILED: native library unavailable", file=sys.stderr)
        return 1
    sizes = [int(x) for x in args.msm_sizes.split(",") if x]
    cols = [int(x) for x in args.msm_cols.split(",") if x]
    kmax = max(cols)
    rng = random.Random(0xB08)
    nmax = 1 << max(sizes)
    t0 = time.perf_counter()
    seed_sc = native.ints_to_limbs(
        [rng.randrange(1, FR) for _ in range(nmax)])
    bases_all = native.g1_fixed_base_muls(FQ, G1_GEN, seed_sc)
    cols_all = np.stack([
        native.ints_to_limbs([rng.randrange(0, FR) for _ in range(nmax)])
        for _ in range(kmax)])
    fixture_s = time.perf_counter() - t0

    curve = []
    for logn in sizes:
        n = 1 << logn
        bases = np.ascontiguousarray(bases_all[:n])
        for kcols in cols:
            scal = np.ascontiguousarray(cols_all[:kcols, :n])
            serial_s = multi_s = None
            serial_pts = multi_pts = None
            for _ in range(max(1, args.msm_reps)):
                t0 = time.perf_counter()
                serial_pts = [native.g1_msm(FQ, bases, scal[k])
                              for k in range(kcols)]
                dt = time.perf_counter() - t0
                serial_s = dt if serial_s is None else min(serial_s, dt)
                t0 = time.perf_counter()
                multi_pts = native.g1_msm_multi(FQ, bases, scal)
                dt = time.perf_counter() - t0
                multi_s = dt if multi_s is None else min(multi_s, dt)
            if multi_pts != serial_pts:
                print(f"BENCH FAILED: column mismatch at n=2^{logn} "
                      f"K={kcols}", file=sys.stderr)
                return 1
            cell = {"log2_n": logn, "k_columns": kcols,
                    "serial_s": round(serial_s, 3),
                    "multi_s": round(multi_s, 3),
                    "aggregate_speedup": round(serial_s / multi_s, 3)}
            curve.append(cell)
            print(json.dumps(cell), file=sys.stderr)

    headline_k = 4 if 4 in cols else kmax
    top = next(c for c in curve
               if c["log2_n"] == max(sizes)
               and c["k_columns"] == headline_k)
    meta = {
        "mode": "msm",
        "curve": curve,
        "fixture_s": round(fixture_s, 1),
        "pn_msm_c": os.environ.get("PN_MSM_C"),
        "host_cores": os.cpu_count(),
        "bit_exact": "every multi column compared == its serial "
                     "g1_msm oracle before timing counts",
        "methodology": "single thread, one box, best-of-reps per cell "
                       "for BOTH sides; serial side is the committed-"
                       "baseline g1_msm (untouched by this round); "
                       "multi side is g1_msm_multi — base parse + "
                       "Montgomery/w-domain conversion amortized over "
                       "all K columns, on-the-fly signed recode, "
                       "bucket-range-tiled batch-affine levels, "
                       "32-chain IFMA bucket reduction; cross-column "
                       "sharing INSIDE one window pass measured net-"
                       "negative on this box (cache/TLB), so the "
                       "default sweeps one column per pass "
                       "(PN_MSM_KB re-enables wider sharing)",
    }
    print(json.dumps(meta), file=sys.stderr)
    value = top["aggregate_speedup"]
    print(json.dumps({
        "metric": f"batched {headline_k}-column commit MSM at "
                  f"2^{max(sizes)}, aggregate speedup vs "
                  f"{headline_k} serial g1_msm calls",
        "value": value,
        "unit": "x",
        "vs_baseline": round(value / 1.5, 3),
    }))
    if value < 1.5:
        print("BENCH FAILED: aggregate speedup under the 1.5x floor",
              file=sys.stderr)
        return 1
    return 0


def bench_refresh_ladder(args) -> int:
    """BENCH_r09: the sublinear refresh ladder under sustained
    localized churn at scale — device partial sweeps and the
    partially-observed sampled mode vs the full-sweep fallback that
    previously served every frontier past the partial bound.

    Protocol per frontier scale: a localized weight-revision window
    (edges of one contiguous source block) is absorbed by the anchored
    DeltaEngine, the drained frontier is served by
    ``incremental.ladder_refresh`` (device kernel forced on —
    ``device_threshold=0``), and the SAME warm vector is then run
    through the full device sweep on the patched operator — the
    fallback the ladder replaces. Asserted per scale: the ladder
    serves (no silent degradation to full), its scores sit within the
    declared L1 budget of the full-sweep oracle, and the whole churn
    window triggers ZERO operator plan builds. The ladder is run once
    un-timed first (the device kernel compiles per pow2 bucket shape;
    XLA compile is a one-time cost the jit cache amortizes, reported
    separately as ``ladder_cold_s``) and best-of-2 timed after.

    Headline ``value`` = the worst (minimum) ladder-vs-full speedup
    across the scales; ``vs_baseline`` = value / 5.0, the acceptance
    floor (>1 means every scale beat 5x). The per-scale cells are the
    freshness-vs-compute frontier: ladder wall tracking frontier size
    while the full-sweep wall tracks graph size."""
    import jax

    from protocol_tpu.graph import barabasi_albert_edges, filter_edges
    from protocol_tpu.incremental import DeltaEngine, ladder_refresh
    from protocol_tpu.ops.routed import (
        RoutedOperator,
        build_routed_operator,
    )
    from protocol_tpu.utils import trace

    def builds_total():
        return trace.counter_total("operator_full_builds")

    if args.alpha <= 0:
        print("BENCH FAILED: the churn ladder needs alpha > 0 — the "
              "declared budget is the damped Neumann bound "
              "spend/alpha, undefined without damping", file=sys.stderr)
        return 1
    # the zero-builds assertion below reads the operator_full_builds
    # counter — a disabled tracer no-ops every inc() and the check
    # could never fire
    trace.enable()
    scales = [int(x) for x in args.churn_frontiers.split(",") if x]
    if not scales:
        print("BENCH FAILED: --churn-frontiers parsed empty",
              file=sys.stderr)
        return 1
    rng = np.random.default_rng(7)
    src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
    valid = np.ones(args.n, dtype=bool)
    fsrc, fdst, _, _, _, raw, _ = filter_edges(
        args.n, src, dst, val, valid, return_raw=True)
    cur = raw.copy()

    rop = None
    cache_path = None
    build_s = 0.0
    if args.cache_dir:
        cache_path = routed_cache_path(args.cache_dir, args.n, args.m)
        if cache_path.exists():
            rop = RoutedOperator.load(cache_path)
    if rop is None:
        t0 = time.perf_counter()
        rop = build_routed_operator(args.n, src, dst, val, valid)
        build_s = time.perf_counter() - t0
        if cache_path is not None:
            cache_path.parent.mkdir(parents=True, exist_ok=True)
            rop.save(cache_path)

    eng = DeltaEngine.anchor(args.n, src, dst, val, valid, rop,
                             alpha=args.alpha,
                             tail_max=1 << 20, tail_fraction=1.0)
    t0 = time.perf_counter()
    s_pub, it0, d0 = eng.converge(
        eng.initial_node_scores(1000.0), args.max_iters, args.tol)
    cold_converge_s = time.perf_counter() - t0
    if float(d0) > args.tol:
        print("BENCH FAILED: anchor converge missed tolerance",
              file=sys.stderr)
        return 1
    eng.take_frontier()
    builds0 = builds_total()
    limit = max(1, int(args.frontier_limit_fraction * args.n))

    factors = [float(x) for x in args.churn_factors.split(",") if x]
    if not factors:
        print("BENCH FAILED: --churn-factors parsed empty",
              file=sys.stderr)
        return 1
    cells = []
    for i, target in enumerate(scales):
        # localized block: one contiguous source range, rotated per
        # scale so windows stay disjoint; ~target/|fanout| revisions
        # seed a frontier near the requested scale. Revisions are
        # MULTIPLICATIVE (±factor): sustained churn re-attests
        # drifting weights rather than rewriting them from scratch —
        # and the factor is what separates locally-decaying influence
        # (the device_partial rung) from graph-flooding influence
        # (the sampled rung)
        factor = factors[i % len(factors)]
        k = max(target // 12, 1)
        span = max(2 * k // args.m, 16)
        base = int(args.n * 0.08) + i * max(int(args.n * 0.22), span)
        lo = np.searchsorted(fsrc, base)
        hi = np.searchsorted(fsrc, base + span)
        if hi - lo < k:
            hi = min(lo + 4 * k, len(fsrc))
        idx = rng.choice(np.arange(lo, hi), min(k, hi - lo),
                         replace=False)
        if not len(idx):
            print(f"BENCH FAILED: empty revision window at frontier "
                  f"target {target} (rotated source block past the "
                  f"edge array — graph too small for this scale)",
                  file=sys.stderr)
            return 1
        deltas = []
        for e in idx:
            new = float(cur[e]) * (
                1.0 - factor + 2.0 * factor * rng.random())
            deltas.append((int(fsrc[e]), int(fdst[e]),
                           float(cur[e]), new))
            cur[e] = new
        t0 = time.perf_counter()
        if not eng.apply_deltas(deltas):
            print("BENCH FAILED: delta batch rejected", file=sys.stderr)
            return 1
        apply_s = time.perf_counter() - t0
        frontier, ok = eng.take_frontier()
        if not ok:
            print("BENCH FAILED: frontier lost partial footing",
                  file=sys.stderr)
            return 1

        def run_ladder():
            t1 = time.perf_counter()
            res, mode = ladder_refresh(
                eng, s_pub, frontier, args.tol, args.max_iters, limit,
                device_threshold=0, sample_budget=args.sample_budget,
                error_budget=args.error_budget)
            return res, mode, time.perf_counter() - t1
        res, mode, ladder_cold_s = run_ladder()  # compile warm-up
        if res is None:
            print(f"BENCH FAILED: ladder fell back to full at "
                  f"frontier target {target} "
                  f"(|frontier|={len(frontier)})", file=sys.stderr)
            return 1
        ladder_s = None
        for _ in range(2):
            res, mode, dt = run_ladder()
            ladder_s = dt if ladder_s is None else min(ladder_s, dt)
        t1 = time.perf_counter()
        s_full, it_f, d_f = eng.converge(s_pub, args.max_iters,
                                         args.tol)
        full_s = time.perf_counter() - t1
        norm = float(np.sum(np.abs(s_full)))
        l1_err = float(np.sum(np.abs(res.scores - s_full))) / norm
        # declared budget: the accumulated first-order leak amplified
        # by the damping horizon (mass leaked outside the observed set
        # keeps propagating under the operator; the damped Neumann
        # series bounds its total effect by spend/alpha) plus both
        # sides' stopping windows (per-sweep delta <= tol with
        # contraction r <= 1-alpha leaves each up to tol/alpha from
        # the fixed point)
        declared = (res.budget_spent + 2.0 * args.tol) / args.alpha
        cell = {
            "frontier_target": target,
            "frontier": int(len(frontier)),
            "frontier_peak": int(res.frontier_peak),
            "revisions": int(len(idx)),
            "mode": mode,
            "sweeps": int(res.sweeps),
            "full_iterations": int(it_f),
            "apply_s": round(apply_s, 4),
            "ladder_cold_s": round(ladder_cold_s, 4),
            "ladder_s": round(ladder_s, 4),
            "full_s": round(full_s, 4),
            "speedup": round(full_s / ladder_s, 1),
            "l1_err_vs_full": l1_err,
            "declared_budget": declared,
            "budget_spent": res.budget_spent,
        }
        cells.append(cell)
        print(json.dumps(cell), file=sys.stderr)
        if l1_err > declared:
            print(f"BENCH FAILED: L1 error {l1_err:.3e} outside the "
                  f"declared budget {declared:.3e}", file=sys.stderr)
            return 1
        s_pub = s_full  # the oracle is the next window's baseline

    builds1 = builds_total()
    meta = {
        "mode": "refresh_ladder",
        "n_peers": args.n,
        "edges": len(fsrc),
        "alpha": args.alpha,
        "tol": args.tol,
        "frontier_limit": limit,
        "sample_budget": args.sample_budget,
        "error_budget": args.error_budget,
        "plan_build_s": round(build_s, 1),
        "anchor_converge_s": round(cold_converge_s, 1),
        "anchor_iterations": int(it0),
        "full_builds_during_churn": builds1 - builds0,
        "cells": cells,
        "device": str(jax.devices()[0]),
        "methodology": "per scale: localized revision window absorbed "
                       "by the anchored engine; ladder_refresh "
                       "(device_threshold=0) vs a warm full device "
                       "sweep on the SAME patched operator from the "
                       "SAME warm vector; ladder best-of-2 after a "
                       "compile warm-up pass, full sweep single run "
                       "(its noise only helps the ladder); scores "
                       "asserted within declared budget "
                       "((budget_spent + 2*tol)/alpha — first-order "
                       "leak amplified by the damping horizon, plus "
                       "stopping windows); oracle result becomes the "
                       "next window's warm start",
    }
    print(json.dumps(meta), file=sys.stderr)
    if builds1 != builds0:
        print("BENCH FAILED: churn window paid operator builds",
              file=sys.stderr)
        return 1
    worst = min(c["speedup"] for c in cells)
    print(json.dumps({
        "metric": f"{_fmt_peers(args.n)}-peer sublinear refresh: worst "
                  f"ladder-vs-full-sweep speedup across "
                  f"{len(cells)} frontier scales",
        "value": worst,
        "unit": "x",
        "vs_baseline": round(worst / 5.0, 2),
    }))
    if worst < 5.0:
        print("BENCH FAILED: ladder speedup under the 5x floor",
              file=sys.stderr)
        return 1
    return 0


def bench_churn(args) -> int:
    """Steady-state churn cost: with a DeltaEngine anchored on one full
    routed build, a batch of weight revisions costs O(batch) host work
    plus O(dirty) device scatters — measured here against the full
    plan build the pre-PR 6 write path would have paid per change.
    ``vs_baseline`` = full_build_s / delta_apply_s (>1 means a churn
    window is cheaper than the rebuild it replaces).

    ``--churn-frontiers`` switches to the sublinear-refresh ladder
    protocol (:func:`bench_refresh_ladder`, BENCH_r09)."""
    import jax

    from protocol_tpu.graph import barabasi_albert_edges, filter_edges
    from protocol_tpu.incremental import DeltaEngine, revision_batch
    from protocol_tpu.ops.routed import build_routed_operator

    rng = np.random.default_rng(7)
    src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
    valid = np.ones(args.n, dtype=bool)
    fsrc, fdst, _, _, _, raw, _ = filter_edges(
        args.n, src, dst, val, valid, return_raw=True)
    cur = raw.copy()

    t0 = time.perf_counter()
    rop = build_routed_operator(args.n, src, dst, val, valid)
    build_s = time.perf_counter() - t0

    eng = DeltaEngine.anchor(args.n, src, dst, val, valid, rop)
    # one converge to settle jit caches; churn timing is host+scatter
    scores, iters, delta = eng.converge(
        eng.initial_node_scores(1000.0), args.max_iters, args.tol)

    apply_s = []
    for _ in range(args.churn_batches):
        deltas = revision_batch(rng, fsrc, fdst, cur, args.churn_edges)
        t1 = time.perf_counter()
        if not eng.apply_deltas(deltas):
            print("BENCH FAILED: delta batch rejected", file=sys.stderr)
            return 1
        apply_s.append(time.perf_counter() - t1)
    wall = float(np.median(apply_s))

    meta = {
        "mode": "churn",
        "n_peers": args.n,
        "edges": len(fsrc),
        "batch_edges": args.churn_edges,
        "batches": args.churn_batches,
        "full_build_s": round(build_s, 3),
        "delta_apply_s": [round(t, 5) for t in apply_s],
        "converge_iterations": int(iters),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(meta), file=sys.stderr)
    print(json.dumps({
        "metric": f"{_fmt_peers(args.n)}-peer steady churn: delta-apply "
                  f"per {args.churn_edges}-revision batch "
                  f"(vs full plan rebuild)",
        "value": round(wall, 5),
        "unit": "s",
        "vs_baseline": round(build_s / wall, 1),
    }))
    return 0


def bench_reads(args) -> int:
    """BENCH_r11: read-path scale-out over follower replicas.

    Protocol: one real CLI leader daemon over the mock devnet, plus
    ``--read-followers`` follower daemons (``serve --follow``) tailing
    its shipped WAL. Per cell, ``--read-clients`` threads hammer
    ``GET /score/<addr>`` for ``--read-seconds`` — against the LEADER
    in the 0-follower baseline cell, round-robin across the FOLLOWERS
    otherwise — while a churn thread posts ``--churn-rate``
    attestations/second to the leader throughout, so the measurement
    never sees an idle write path. Recorded per cell: read QPS, p95 of
    the sampled ``ptpu_repl_lag_{records,seconds}`` gauges (follower
    cells), and the leader's mean refresh wall over the window (the
    interference signal: reads pointed at followers stop contending
    with the refresh loop). After the sweep, churn stops and the
    byte-equality criterion is ASSERTED: every follower's full
    ``/scores`` vector must equal the leader's at the same WAL
    position (all daemons run all-cold deterministic refreshes).

    1-core honesty (the established methodology): every daemon shares
    this box's single core, so follower serving steals cycles the
    leader could have used — the QPS curve here measures that the
    fabric WORKS under churn and what serving costs, not the N-core
    speedup. Serving is I/O-wait-dominated (socket accept + JSON
    encode interleave across processes), so scaling is real but
    muted; on an N-core/N-box deployment each follower adds a full
    core's serving capacity while the leader keeps its own — that
    curve is owed to hardware. Headline ``value`` = the refresh-wall
    interference ratio (leader refresh mean with reads at the leader /
    with reads at the top follower count; floor 2×); the raw QPS
    scaling is recorded in the meta, not gated.
    """
    import urllib.request

    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(repo, "tools"))
    import serve_smoke

    from protocol_tpu.client import Client, ClientConfig
    from protocol_tpu.client.chain import RpcChain
    from protocol_tpu.client.eth import (
        address_from_public_key,
        ecdsa_keypairs_from_mnemonic,
    )
    from protocol_tpu.client.mocknode import MockNode

    counts = sorted({int(x) for x in args.read_followers.split(",")
                     if x != ""})
    if not counts or counts[0] != 0:
        print("BENCH FAILED: --read-followers must include 0 (the "
              "leader-only baseline the headline divides by)",
              file=sys.stderr)
        return 1

    def step(msg):
        print(f"reads: {msg}", file=sys.stderr, flush=True)

    node = MockNode()
    node_url = node.start()
    deployer = ecdsa_keypairs_from_mnemonic(serve_smoke.MNEMONIC, 1)[0]
    chain = RpcChain.deploy_signed(node_url, deployer)
    config = ClientConfig(
        as_address="0x" + chain.contract_address.hex(),
        node_url=node_url, domain="0x" + "00" * 20)
    client = Client(config, serve_smoke.MNEMONIC)
    kps = ecdsa_keypairs_from_mnemonic(serve_smoke.MNEMONIC, 3)
    addrs = [address_from_public_key(kp.public_key) for kp in kps]

    def get_json(url, path):
        with urllib.request.urlopen(url + path, timeout=10) as r:
            body = r.read()
        return body.decode() if path == "/metrics" else json.loads(body)

    def refresh_snapshot(lurl):
        """(count, sum, {le: cum}) over every mode of
        ptpu_refresh_seconds — histogram-bucket deltas between two
        snapshots give the WINDOWED distribution (the Prometheus-side
        quantile discipline, computed here without a server)."""
        text = get_json(lurl, "/metrics")
        count = serve_smoke._series_sum(text,
                                        "ptpu_refresh_seconds_count")
        total = serve_smoke._series_sum(text,
                                        "ptpu_refresh_seconds_sum")
        buckets: dict = {}
        for line in text.splitlines():
            if not line.startswith("ptpu_refresh_seconds_bucket"):
                continue
            le = line.split('le="', 1)[1].split('"', 1)[0]
            buckets[le] = buckets.get(le, 0.0) + float(line.split()[-1])
        return count or 0.0, total or 0.0, buckets

    def hist_p95(b0, b1):
        """p95 upper bound from two cumulative-bucket snapshots; the
        +Inf bucket renders as the string "+Inf" (json.dumps would
        emit non-standard Infinity for the float)."""
        deltas = [(float("inf") if le == "+Inf" else float(le),
                   b1.get(le, 0.0) - b0.get(le, 0.0))
                  for le in b1]
        deltas.sort()
        total = deltas[-1][1] if deltas else 0.0
        if total <= 0:
            return None
        for le, cum in deltas:
            if cum >= 0.95 * total:
                return "+Inf" if le == float("inf") else le
        return None

    # all-cold deterministic refreshes (the byte-equality contract)
    det_env = {"PTPU_SERVE_COLD_EDIT_FRACTION": "0.0",
               "PTPU_SERVE_SNAPSHOT_EVERY": "8"}
    churn_round = [0]

    def churn_once():
        r = churn_round[0]
        churn_round[0] += 1
        i = r % 3
        about = addrs[(r + 1) % 3]
        client.keypairs[0] = kps[i]
        client.attest(about, 2 + (r * 7) % 11)

    procs = []
    try:
        return _bench_reads_body(args, node, config, client, kps,
                                 addrs, det_env, churn_once, get_json,
                                 refresh_snapshot, hist_p95, step,
                                 procs)
    finally:
        # a failed cell must not leak live daemons onto the box (they
        # would skew every later bench) or delete state dirs under a
        # live WAL writer
        for proc in procs:
            if proc.returncode is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=60)
            except Exception:  # noqa: BLE001 - teardown best-effort
                proc.kill()
        try:
            node.stop()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def _bench_reads_body(args, node, config, client, kps, addrs, det_env,
                      churn_once, get_json, refresh_snapshot, hist_p95,
                      step, procs) -> int:
    import tempfile
    import threading
    import urllib.request

    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(repo, "tools"))
    import serve_smoke

    from protocol_tpu.client.storage import JSONFileStorage

    counts = sorted({int(x) for x in args.read_followers.split(",")
                     if x != ""})
    with tempfile.TemporaryDirectory(prefix="ptpu-bench-reads-") \
            as assets:
        JSONFileStorage(os.path.join(assets, "config.json")).save(
            config.to_dict())
        leader, lurl, _ = serve_smoke._spawn_daemon(
            assets, det_env, step, "leader")
        procs.append(leader)
        for _ in range(6):
            churn_once()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                if get_json(lurl, "/scores")["scores"]:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        step("leader serving")

        followers = []
        raw_means: dict = {}  # follower count -> unrounded refresh mean

        def caught_up(furl):
            try:
                fs = get_json(furl, "/status")
                ls = get_json(lurl, "/status")
                return (fs["repl"]["cursor"]
                        == ls["store"]["wal_position"])
            except Exception:
                return False

        def measure(n_f) -> dict:
            targets = ([furl for _, furl in followers[:n_f]]
                       if n_f else [lurl])
            stop = threading.Event()
            reads = [0] * args.read_clients
            errors = [0]
            lag_samples = []

            def reader(c):
                k = c
                while not stop.is_set():
                    url = targets[k % len(targets)]
                    addr = addrs[k % len(addrs)]
                    k += 1
                    try:
                        with urllib.request.urlopen(
                                url + f"/score/0x{addr.hex()}",
                                timeout=10) as r:
                            r.read()
                        reads[c] += 1
                    except Exception:
                        errors[0] += 1

            def churner():
                period = 1.0 / max(args.churn_rate, 0.1)
                while not stop.is_set():
                    try:
                        churn_once()
                    except Exception:
                        pass
                    stop.wait(period)

            def sampler():
                # every follower in the cell contributes samples —
                # p95 over the fleet, not just replica 0
                furls = [furl for _, furl in followers[:n_f]]
                while not stop.is_set():
                    for furl in furls:
                        try:
                            fs = get_json(furl, "/status")["repl"]
                            lag_samples.append(
                                (fs["lag_records"],
                                 max(fs["lag_seconds"], 0.0)))
                        except Exception:
                            pass
                    stop.wait(0.1)

            c0, s0, b0 = refresh_snapshot(lurl)
            threads = [threading.Thread(target=reader, args=(c,),
                                        daemon=True)
                       for c in range(args.read_clients)]
            threads.append(threading.Thread(target=churner,
                                            daemon=True))
            if n_f:
                threads.append(threading.Thread(target=sampler,
                                                daemon=True))
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(args.read_seconds)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            wall = time.perf_counter() - t0
            c1, s1, b1 = refresh_snapshot(lurl)
            refreshes = c1 - c0
            if refreshes <= 0:
                raise RuntimeError(
                    "no leader refreshes in the window — churn thread "
                    "dead, interference cells would be vacuous")
            mean_s = (s1 - s0) / refreshes
            raw_means[n_f] = mean_s  # unrounded: the headline ratio
            # must not divide by a 4-decimal-rounded (possibly 0.0)
            # display value
            cell = {
                "followers": n_f,
                "read_target": "followers" if n_f else "leader",
                "reads": int(sum(reads)),
                "read_errors": int(errors[0]),
                "qps": round(sum(reads) / wall, 1),
                "window_s": round(wall, 2),
                "leader_refreshes_in_window": int(refreshes),
                "leader_refresh_mean_s": round(mean_s, 4),
                # windowed p95 upper bucket bound (log-spaced buckets:
                # coarse, but windowed — the honest interference tail)
                "leader_refresh_p95_le_s": hist_p95(b0, b1),
            }
            if lag_samples:
                recs = sorted(r for r, _ in lag_samples)
                secs = sorted(s for _, s in lag_samples)

                def p95(xs):
                    return xs[min(len(xs) - 1,
                                  int(0.95 * (len(xs) - 1)))]
                cell["repl_lag_records_p95"] = p95(recs)
                cell["repl_lag_seconds_p95"] = round(p95(secs), 3)
            return cell

        curve = []
        for n_f in counts:
            while len(followers) < n_f:
                i = len(followers)
                proc, furl, _ = serve_smoke._spawn_daemon(
                    assets, det_env, step, f"follower{i}",
                    state_dir=f"fstate{i}",
                    extra_args=("--follow", lurl))
                procs.append(proc)
                deadline = time.monotonic() + 120
                while not caught_up(furl):
                    if time.monotonic() > deadline:
                        print("BENCH FAILED: follower never caught up",
                              file=sys.stderr)
                        return 1
                    time.sleep(0.2)
                followers.append((proc, furl))
            cell = measure(n_f)
            curve.append(cell)
            print(json.dumps(cell), file=sys.stderr)

        # quiesce, then ASSERT byte equality at the same WAL position
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            ls = get_json(lurl, "/status")
            if (ls["last_refresh"]["revision"]
                    == ls["graph"]["revision"]
                    and all(caught_up(furl)
                            and get_json(furl, "/status")
                            ["last_refresh"]["revision"]
                            == get_json(furl, "/status")
                            ["graph"]["revision"]
                            for _, furl in followers)):
                break
            time.sleep(0.2)
        lscores = get_json(lurl, "/scores")["scores"]
        pos = get_json(lurl, "/status")["store"]["wal_position"]
        for _, furl in followers:
            fscores = get_json(furl, "/scores")["scores"]
            if fscores != lscores:
                print(f"BENCH FAILED: follower scores not byte-equal "
                      f"to the leader at {pos}: {fscores} vs "
                      f"{lscores}", file=sys.stderr)
                return 1
        step(f"byte-equality held across {len(followers)} "
             f"follower(s) at {pos}")
        # graceful teardown INSIDE the temp-dir scope: the state dirs
        # must outlive their live WAL writers (the caller's finally
        # re-terminates idempotently on the failure paths)
        for proc in procs:
            proc.terminate()
        for proc in procs:
            proc.wait(timeout=60)

    by_count = {c["followers"]: c for c in curve}
    top = max(counts)
    qps_scaling = (by_count[top]["qps"] / by_count[0]["qps"]
                   if by_count[0]["qps"] else 0.0)
    # the headline on THIS box is interference, not capacity: reads
    # pointed at followers stop contending with the leader's refresh
    # loop (windowed mean ratio — every process shares one core, so
    # raw QPS cannot scale here; see methodology); unrounded means, a
    # sub-50µs cell must not divide-by-(rounded-)zero
    value = raw_means[0] / max(raw_means[top], 1e-9)
    meta = {
        "mode": "reads",
        "follower_counts": counts,
        "read_clients": args.read_clients,
        "window_s": args.read_seconds,
        "churn_rate_per_s": args.churn_rate,
        "curve": curve,
        "qps_scaling_vs_leader_only": round(qps_scaling, 3),
        "refresh_interference_ratio": round(value, 2),
        "byte_equality": f"every follower /scores vector == leader at "
                         f"WAL {pos} (asserted, full vector)",
        "host_cores": os.cpu_count(),
        "methodology": "real CLI daemons (leader + serve --follow "
                       "followers) over the mock devnet, one box; "
                       "reads are GET /score/<addr> over fresh "
                       "connections; churn ingest runs on the leader "
                       "through every window; all daemons refresh "
                       "all-cold (deterministic trajectories) so byte "
                       "equality is assertable; single-core caveat: "
                       "all processes share 1 core, so follower "
                       "serving steals cycles instead of adding them "
                       "— qps_scaling_vs_leader_only measures that "
                       "cost honestly, while the headline is the "
                       "refresh-wall interference reads stop causing "
                       "when pointed at followers; on N cores/boxes "
                       "each follower adds a full core of serving "
                       "capacity (curve owed to hardware)",
    }
    print(json.dumps(meta), file=sys.stderr)
    print(json.dumps({
        "metric": f"leader refresh-wall interference: mean refresh "
                  f"wall with reads at the leader vs at {top} "
                  f"followers, under {args.churn_rate:.0f}/s churn",
        "value": round(value, 2),
        "unit": "x",
        "vs_baseline": round(value / 2.0, 3),
    }))
    if value < 2.0:
        # advisory, not a gate: the interference ratio needs enough
        # read pressure per window to inflate the leader cell (short
        # --read-seconds runs legitimately measure ~1x); the HARD
        # criterion of this bench is the byte-equality assert above,
        # which already returned 1 on violation
        print(f"reads: NOTE interference ratio {value:.2f}x under the "
              "2x reference (window too short / box too quiet to "
              "pressure the leader?)", file=sys.stderr)
    return 0


def bench_proofs(args) -> int:
    """Proof-pool throughput: concurrent clients vs worker count.

    Each job is a REAL host-path prove (``prove_fast``, deterministic
    blinding — byte parity with the pre-pool single-worker output is
    asserted before anything is timed) of a smoke-scale circuit,
    wrapped in a ``--device-window`` seconds device-occupancy window:
    ``time.sleep`` releasing the GIL, standing in for the
    device-resident phase of a production prove (the r5 battery's
    k=20/21 proves spend minutes blocked on device compute per second
    of host orchestration). On a multi-device box each worker's window
    runs on its own chip; on this host-path box the window is what
    makes per-worker overlap physically possible at all — a 1-core
    container cannot overlap host arithmetic, so with ``--device-window
    0`` the curve measures scheduling overhead only (expect ~1.0x; the
    measured flat host-only number is reported in the meta either way).

    Two job kinds run two distinct circuits, so the affinity scheduler
    has real cache keys to route on; clients retry 429 sheds, so the
    shed counters exercise the tiered admission path under the burst.

    Headline: proofs/hour at each worker count; ``value`` = the
    2-worker scaling factor (2 workers vs 1), ``vs_baseline`` =
    value / 1.8 — the BENCH_r07 acceptance floor (>1 means the pool
    beat it).
    """
    import threading

    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from protocol_tpu.cli.profilecmd import synthetic_circuit
    from protocol_tpu.service.faults import FaultInjector
    from protocol_tpu.service.pool import ProofWorkerPool, QueueFullError
    from protocol_tpu.service.provers import PROOF_PRIORITIES
    from protocol_tpu.utils.errors import EigenError
    from protocol_tpu.zk import prover_fast as pf

    params = pf.setup_params_fast(args.proof_k, seed=b"pool-bench")
    kinds = {}
    references = {}
    for kind, seed in (("eigentrust", 11), ("threshold", 12)):
        cs = synthetic_circuit(gates=args.proof_gates, seed=seed)
        pk = pf.keygen_fast(params, cs)
        kinds[kind] = (pk, cs)
        references[kind] = pf.prove_fast(params, pk, cs,
                                         randint=lambda: 424242)

    window = max(0.0, args.device_window)

    def make_prover(kind):
        pk, cs = kinds[kind]

        def prove(p):
            proof = pf.prove_fast(params, pk, cs,
                                  randint=lambda: 424242)
            if window:
                time.sleep(window)  # the device-occupancy stand-in
            return {"proof": proof.hex()}

        return prove

    registry = {k: make_prover(k) for k in kinds}
    # tier-0 kind: instant, shed FIRST once the queue passes the
    # watermark — the burst below proves the tiered admission path
    registry["profile"] = lambda p: {"ok": True}
    no_faults = FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0})

    def run_pool(n_workers: int, n_jobs: int):
        pool = ProofWorkerPool(
            registry, capacity=8, workers=n_workers, faults=no_faults,
            priorities=PROOF_PRIORITIES,
            worker_env=lambda w: pf.worker_isolation(w.name, w.device))
        pool.start()
        ids: list = []
        ids_lock = threading.Lock()

        def client(c):
            got = []
            for i in range(n_jobs // args.clients):
                kind = "eigentrust" if (c + i) % 2 else "threshold"
                while True:
                    try:
                        got.append(pool.submit(kind, {}).job_id)
                        break
                    except QueueFullError:
                        time.sleep(0.02)  # shed: retry like a client
                    except EigenError:
                        time.sleep(0.05)  # byte ceiling: back off
            with ids_lock:
                ids.extend(got)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        # tier-0 burst against the deep queue: profile jobs shed with
        # 429 while the proof kinds keep landing — the graduated floor
        time.sleep(0.3)
        profile_shed = 0
        for _ in range(4):
            try:
                pool.submit("profile", {})
            except QueueFullError:
                profile_shed += 1
        for t in threads:
            t.join()
        # a stalled pool (the scheduling regression this benchmark
        # exists to catch) must FAIL the bench, not hang it
        stall_deadline = time.monotonic() + 600.0
        while not all(pool.get(j).status in ("done", "failed")
                      for j in ids):
            if time.monotonic() > stall_deadline:
                raise RuntimeError("proof pool stalled (jobs never "
                                   "reached a terminal state)")
            time.sleep(0.01)
        wall = time.perf_counter() - t0
        # byte parity: every pool proof matches the single-worker
        # reference for its kind
        for jid in ids:
            job = pool.get(jid)
            assert job.status == "done", (jid, job.error)
            assert bytes.fromhex(job.result["proof"]) \
                == references[job.kind], f"{jid}: proof bytes diverged"
        status = pool.pool_status()
        pool.drain(10.0)
        hits = sum(w["affinity_hits"] for w in status["workers"])
        misses = sum(w["affinity_misses"] for w in status["workers"])
        return {
            "workers": n_workers,
            "jobs": len(ids),
            "wall_s": round(wall, 3),
            "proofs_per_hour": round(len(ids) / wall * 3600.0, 1),
            "affinity_hit_rate": round(hits / max(hits + misses, 1), 3),
            "stolen": sum(w["stolen"] for w in status["workers"]),
            "shed": status["shed"],
            "profile_burst_shed_429": profile_shed,
            "per_worker_jobs": {w["worker"]: w["jobs_run"]
                                for w in status["workers"]},
        }

    worker_counts = [int(x) for x in args.workers_list.split(",") if x]
    # warm the prover caches/jit before timing
    run_pool(1, max(args.clients, 4))
    curve = [run_pool(nw, args.proof_jobs) for nw in worker_counts]

    by_workers = {c["workers"]: c for c in curve}
    speedup_2w = None
    if 2 in by_workers and 1 in by_workers:
        speedup_2w = (by_workers[2]["proofs_per_hour"]
                      / by_workers[1]["proofs_per_hour"])
    meta = {
        "mode": "proofs",
        "k": args.proof_k,
        "gates": args.proof_gates,
        "clients": args.clients,
        "device_window_s": window,
        "curve": curve,
        "byte_parity": "identical to single-worker prove_fast output",
        "host_cores": os.cpu_count(),
        "speedup_2w": (round(speedup_2w, 3)
                       if speedup_2w is not None else None),
    }
    print(json.dumps(meta), file=sys.stderr)
    value = speedup_2w if speedup_2w is not None else 1.0
    print(json.dumps({
        "metric": "proof pool proofs/hour scaling, 2 workers vs 1 "
                  f"(host path, k={args.proof_k} circuits, "
                  f"{window:.2f}s device window)",
        "value": round(value, 3),
        "unit": "x",
        "vs_baseline": round(value / 1.8, 3),
    }))
    if speedup_2w is not None and speedup_2w < 1.8:
        print("BENCH FAILED: 2-worker scaling under the 1.8x floor",
              file=sys.stderr)
        return 1
    return 0


def bench_sharded(args) -> int:
    """BENCH_r10: intra-prove sharding — ONE prove's wall clock vs
    worker count, with worker lending fanning the prove's commit work
    units across the pool.

    Methodology (the BENCH_r07 device-window discipline one level
    down, now INSIDE a single prove): the flagship-shape workload is a
    real CommitEngine flush of ``--shard-cols`` columns at
    2^``--shard-k`` over real SRS bases, dispatched with
    ``flush_async()`` and merged through the deterministic rendezvous,
    with a ``--shard-window`` seconds device-occupancy window between
    dispatch and merge — ``time.sleep`` releasing the GIL, standing in
    for the device-resident quotient/ext phase a real flagship prove
    holds there (BASELINE r4: the warm k=20 device prove is ~30 s of
    host commits against a comparable device-resident phase; window
    auto-sizes to the measured inline commit wall to reproduce that
    regime). On this 1-core box that window is what makes intra-prove
    overlap physically possible at all: a single worker must run the
    window THEN the MSMs serially, while lent workers chew the
    GIL-released ``g1_msm_multi`` shards UNDER it. On a real
    multi-device box the same fan-out overlaps MSM shards with other
    workers' cores outright — that curve is owed to hardware, like
    BENCH_r07's. Every cell's transcript digest must equal the inline
    (runner-free) reference — sharding may move work, never a
    transcript byte. 4 workers ≈ 2 workers here by construction (one
    window, one spare core's worth of GIL-released compute); recorded
    anyway so the shape of the curve is honest.

    A second leg proves byte parity end-to-end on the REAL prove path:
    a full ``prove_fast`` sharded through the pool must produce the
    exact bytes of the direct single-worker call (its sharded-vs-single
    wall on this box is ~1.0x — host arithmetic cannot overlap itself
    on one core — and is reported, not hidden).

    Headline: flagship-shape wall at 1 worker / wall at 2 workers;
    acceptance floor 1.3x.
    """
    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from protocol_tpu.cli.profilecmd import synthetic_circuit
    from protocol_tpu.service.faults import FaultInjector
    from protocol_tpu.service.pool import ProofWorkerPool
    from protocol_tpu.zk import prover_fast as pf
    from protocol_tpu.zk.commit_engine import CommitEngine
    from protocol_tpu.zk.transcript import make_transcript

    import random as _random

    k, cols_n = args.shard_k, args.shard_cols
    print(f"setup: params 2^{k}, {cols_n} columns", file=sys.stderr)
    params = pf.setup_params_fast(k, seed=b"shard-bench")
    rng = _random.Random(17)
    n = 1 << k
    blob = np.frombuffer(
        rng.getrandbits(8 * 32 * n * cols_n).to_bytes(
            32 * n * cols_n, "little"),
        dtype="<u8").reshape(cols_n, n, 4).copy()
    blob[:, :, 3] &= (1 << 59) - 1  # keep scalars < R
    cols = [np.ascontiguousarray(blob[i]) for i in range(cols_n)]
    no_faults = FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0})

    def flush_digest(window: float) -> tuple:
        """One flagship-shape prove body: dispatch → window → merge →
        absorb in submission order → transcript digest."""
        eng = CommitEngine(params)
        for i, c in enumerate(cols):
            eng.submit_coeffs(f"c{i}", c)
        handle = eng.flush_async()
        if window:
            time.sleep(window)  # the device-occupancy stand-in
        pts = handle.result()
        tr = make_transcript("poseidon")
        for pt in pts:
            tr.absorb_point(pt)
        return tr.challenge()

    # inline reference: no runner → everything computes at result();
    # also measures the commit wall the auto window reproduces. One
    # unmeasured warm-up first: the initial flush pays the one-time
    # SRS limb conversion, which is params-cached for every later cell
    flush_digest(0.0)
    t0 = time.perf_counter()
    ref_digest = flush_digest(0.0)
    t_flush = time.perf_counter() - t0
    window = args.shard_window or round(t_flush, 3)
    print(f"inline commit wall {t_flush:.3f}s -> window {window:.3f}s",
          file=sys.stderr)

    def run_cell(n_workers: int) -> dict:
        pool = ProofWorkerPool(
            {"flagship": lambda p: {"digest": str(flush_digest(window))}},
            capacity=8, workers=n_workers, faults=no_faults,
            shard_kinds={"flagship"}, shard_cap=4)
        pool.start()
        best = None
        digest = None
        for _ in range(max(1, args.shard_reps)):
            job = pool.submit("flagship", {})
            # a rendezvous/lending regression must FAIL the bench,
            # not hang it (the bench_proofs stall-deadline rule)
            stall = time.monotonic() + 600.0
            while pool.get(job.job_id).status not in ("done", "failed"):
                if time.monotonic() > stall:
                    raise RuntimeError("sharded flagship prove stalled")
                time.sleep(0.01)
            got = pool.get(job.job_id)
            assert got.status == "done", got.error
            digest = got.result["digest"]
            assert digest == str(ref_digest), \
                f"{n_workers}w: transcript digest diverged"
            wall = got.finished_at - got.started_at
            best = wall if best is None else min(best, wall)
        status = pool.pool_status()
        pool.drain(10.0)
        return {
            "workers": n_workers,
            "wall_s": round(best, 3),
            "lent_units": sum(w["shards_run"]
                              for w in status["workers"]),
        }

    worker_counts = [int(x) for x in args.shard_workers.split(",") if x]
    if not {1, 2} <= set(worker_counts):
        # the headline IS wall(1w)/wall(2w): without both cells the
        # bench would fabricate a passing 1.0x — refuse instead
        print("error: --shard-workers must include 1 and 2 (the "
              "headline cells)", file=sys.stderr)
        return 1
    run_cell(worker_counts[0])  # warm (base parse/limb caches)
    curve = [run_cell(nw) for nw in worker_counts]
    by_workers = {c["workers"]: c for c in curve}

    # leg B: the real prove path end-to-end through the pool
    cs = synthetic_circuit(gates=args.proof_gates, seed=11)
    pparams = pf.setup_params_fast(args.proof_k, seed=b"shard-parity")
    ppk = pf.keygen_fast(pparams, cs)
    reference = pf.prove_fast(pparams, ppk, cs, randint=lambda: 424242)
    t0 = time.perf_counter()
    pf.prove_fast(pparams, ppk, cs, randint=lambda: 424242)
    t_direct = time.perf_counter() - t0
    pool = ProofWorkerPool(
        {"eigentrust": lambda p: {"proof": pf.prove_fast(
            pparams, ppk, cs, randint=lambda: 424242).hex()}},
        capacity=8, workers=2, faults=no_faults,
        shard_kinds={"eigentrust"}, shard_cap=4,
        worker_env=lambda w: pf.worker_isolation(w.name, w.device))
    pool.start()
    job = pool.submit("eigentrust", {})
    stall = time.monotonic() + 600.0
    while pool.get(job.job_id).status not in ("done", "failed"):
        if time.monotonic() > stall:
            raise RuntimeError("sharded real prove stalled")
        time.sleep(0.01)
    got = pool.get(job.job_id)
    assert got.status == "done", got.error
    assert bytes.fromhex(got.result["proof"]) == reference, \
        "sharded real prove diverged from the direct prove_fast"
    t_sharded_real = got.finished_at - got.started_at
    pool.drain(10.0)

    speedup_2w = None
    if 1 in by_workers and 2 in by_workers:
        speedup_2w = by_workers[1]["wall_s"] / by_workers[2]["wall_s"]
    meta = {
        "mode": "sharded",
        "shard_k": k,
        "columns": cols_n,
        "window_s": window,
        "inline_commit_wall_s": round(t_flush, 3),
        "curve": curve,
        "transcript_parity": "digest identical to the inline "
                             "(runner-free) flush at every cell",
        "real_prove": {
            "k": args.proof_k, "gates": args.proof_gates,
            "direct_s": round(t_direct, 3),
            "sharded_2w_s": round(t_sharded_real, 3),
            "byte_parity": "identical to direct prove_fast",
        },
        "host_cores": os.cpu_count(),
        "speedup_2w": (round(speedup_2w, 3)
                       if speedup_2w is not None else None),
    }
    print(json.dumps(meta), file=sys.stderr)
    value = speedup_2w if speedup_2w is not None else 1.0
    print(json.dumps({
        "metric": "intra-prove sharding: flagship-shape prove wall, "
                  f"1 worker vs 2 (2^{k} x {cols_n} commit columns, "
                  f"{window:.2f}s device window)",
        "value": round(value, 3),
        "unit": "x",
        "vs_baseline": round(value / 1.3, 3),
    }))
    if speedup_2w is not None and speedup_2w < 1.3:
        print("BENCH FAILED: 2-worker sharded speedup under the 1.3x "
              "floor", file=sys.stderr)
        return 1
    return 0


def bench_fabric(args) -> int:
    """BENCH_r13: the cross-process proving fabric — ONE prove's wall
    clock vs EXTERNAL ``prove-worker`` process count, with the prove's
    commit units serialized through a filesystem FabricStore and
    executed by real OS processes sharing nothing but that directory.

    Methodology (BENCH_r10's device-window discipline, across process
    boundaries): the flagship-shape workload is a real CommitEngine
    flush of ``--shard-cols`` columns at 2^``--shard-k`` over real SRS
    bases, dispatched with ``flush_async()`` and merged through the
    deterministic rendezvous, with a ``--shard-window`` seconds
    device-occupancy window between dispatch and merge (``time.sleep``
    standing in for the device-resident quotient/ext phase; it
    auto-sizes to the measured inline commit wall). On this 1-core box
    the window is what makes cross-process overlap physically possible:
    the daemon process is IDLE inside it — not merely GIL-released —
    so external worker processes get the core outright and chew the
    published MSM units under it. At 0 external workers the same prove
    must run the window THEN the units serially. On a multi-core box
    the fleet overlaps with the daemon's own compute too; that curve is
    owed to hardware, like BENCH_r07's and r10's. Every cell's
    transcript digest must equal the inline (runner-free, fabric-free)
    reference — the fabric may move units between processes, never a
    transcript byte.

    Proofs/hour saturation note: the fleet adds throughput only while
    idle cores exist. On an N-core box, proofs/hour from fabric fan-out
    saturates at ~N x the single-process rate; past that, workers
    time-slice the same silicon and the curve flattens (here N=1, so 2
    and 4 external workers measure protocol overhead and reclaim
    correctness, not added silicon — the 1-worker cell under the
    window is the honest overlap measurement).

    Headline: flagship-shape wall at 0 external workers / wall at 2.
    """
    import contextlib
    import shutil
    import tempfile

    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from protocol_tpu.service.faults import FaultInjector
    from protocol_tpu.service.pool import ProofWorkerPool
    from protocol_tpu.zk import prover_fast as pf
    from protocol_tpu.zk.commit_engine import CommitEngine
    from protocol_tpu.zk.fabric import FabricStore
    from protocol_tpu.zk.transcript import make_transcript

    import random as _random

    k, cols_n = args.shard_k, args.shard_cols
    print(f"setup: params 2^{k}, {cols_n} columns", file=sys.stderr)
    params = pf.setup_params_fast(k, seed=b"fabric-bench")
    rng = _random.Random(17)
    n = 1 << k
    blob = np.frombuffer(
        rng.getrandbits(8 * 32 * n * cols_n).to_bytes(
            32 * n * cols_n, "little"),
        dtype="<u8").reshape(cols_n, n, 4).copy()
    blob[:, :, 3] &= (1 << 59) - 1  # keep scalars < R
    cols = [np.ascontiguousarray(blob[i]) for i in range(cols_n)]
    no_faults = FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0})

    def flush_digest(window: float) -> tuple:
        eng = CommitEngine(params)
        for i, c in enumerate(cols):
            eng.submit_coeffs(f"c{i}", c)
        handle = eng.flush_async()
        if window:
            time.sleep(window)  # the device-occupancy stand-in
        pts = handle.result()
        tr = make_transcript("poseidon")
        for pt in pts:
            tr.absorb_point(pt)
        return tr.challenge()

    flush_digest(0.0)  # warm-up: one-time SRS limb conversion
    t0 = time.perf_counter()
    ref_digest = flush_digest(0.0)
    t_flush = time.perf_counter() - t0
    window = args.shard_window or round(t_flush, 3)
    print(f"inline commit wall {t_flush:.3f}s -> window {window:.3f}s",
          file=sys.stderr)

    def spawn_worker(state_dir: str, name: str):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.abspath(__file__)))
        return subprocess.Popen(
            [sys.executable, "-m", "protocol_tpu.cli",
             "--assets", os.path.join(state_dir, "assets"),
             "prove-worker", "--state-dir", state_dir,
             "--name", name, "--poll", "0.02"],
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def run_cell(n_ext: int) -> dict:
        state = tempfile.mkdtemp(prefix="ptpu-bench-fabric-")
        fabric = FabricStore(os.path.join(state, "fabric"),
                             lease_ttl=5.0)
        pool = ProofWorkerPool(
            {"flagship": lambda p: {"digest": str(flush_digest(window))}},
            capacity=8, workers=1, faults=no_faults,
            shard_kinds={"flagship"}, shard_cap=4, fabric=fabric)
        pool.start()
        procs = [spawn_worker(state, f"fw{i}") for i in range(n_ext)]
        try:
            deadline = time.monotonic() + 90.0
            while fabric.workers_live() < n_ext:
                fabric._workers_cache = (0.0, 0)
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{n_ext} fabric workers never registered")
                time.sleep(0.05)
            best = None
            for _ in range(max(1, args.fabric_reps)):
                job = pool.submit("flagship", {})
                stall = time.monotonic() + 600.0
                while pool.get(job.job_id).status not in ("done",
                                                          "failed"):
                    if time.monotonic() > stall:
                        raise RuntimeError("fabric prove stalled")
                    time.sleep(0.01)
                got = pool.get(job.job_id)
                assert got.status == "done", got.error
                assert got.result["digest"] == str(ref_digest), \
                    f"{n_ext} ext workers: transcript digest diverged"
                wall = got.finished_at - got.started_at
                best = wall if best is None else min(best, wall)
            status = pool.pool_status()["fabric"]
        finally:
            pool.drain(10.0)
            for p in procs:
                p.terminate()
            for p in procs:
                with contextlib.suppress(Exception):
                    p.wait(timeout=30)
            shutil.rmtree(state, ignore_errors=True)
        return {
            "ext_workers": n_ext,
            "wall_s": round(best, 3),
            "units_published": status["units_published"],
            "units_applied_remote": status["results_applied"],
        }

    worker_counts = [int(x) for x in args.fabric_workers.split(",") if x]
    if not {0, 2} <= set(worker_counts):
        # the headline IS wall(0 ext)/wall(2 ext): without both cells
        # the bench would fabricate a passing 1.0x — refuse instead
        print("error: --fabric-workers must include 0 and 2 (the "
              "headline cells)", file=sys.stderr)
        return 1
    run_cell(0)  # warm (base parse/limb caches, subprocess-free)
    curve = [run_cell(nw) for nw in worker_counts]
    by_workers = {c["ext_workers"]: c for c in curve}

    speedup_2w = None
    if 0 in by_workers and 2 in by_workers:
        speedup_2w = by_workers[0]["wall_s"] / by_workers[2]["wall_s"]
    meta = {
        "mode": "fabric",
        "shard_k": k,
        "columns": cols_n,
        "window_s": window,
        "inline_commit_wall_s": round(t_flush, 3),
        "curve": curve,
        "transcript_parity": "digest identical to the inline "
                             "(runner-free, fabric-free) flush at "
                             "every cell",
        "proofs_per_hour_note": "fabric fan-out adds proofs/hour only "
                                "while idle cores exist; on an N-core "
                                "box it saturates at ~N x the single-"
                                "process rate, after which workers "
                                "time-slice the same silicon "
                                f"(host_cores here: {os.cpu_count()})",
        "host_cores": os.cpu_count(),
        "speedup_2w": (round(speedup_2w, 3)
                       if speedup_2w is not None else None),
    }
    print(json.dumps(meta), file=sys.stderr)
    value = speedup_2w if speedup_2w is not None else 1.0
    print(json.dumps({
        "metric": "cross-process fabric: flagship-shape prove wall, "
                  f"0 external workers vs 2 (2^{k} x {cols_n} commit "
                  f"columns, {window:.2f}s device window)",
        "value": round(value, 3),
        "unit": "x",
        "vs_baseline": round(value / 1.3, 3),
    }))
    if speedup_2w is not None and speedup_2w < 1.3:
        print("BENCH FAILED: 2-external-worker fabric speedup under "
              "the 1.3x floor", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
