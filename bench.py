"""Headline benchmark: large-peer trust-graph convergence on TPU.

BASELINE.json north star: converge a 10M-peer power-law trust graph to a
1e-6 relative-L1 delta in under 5 s wall-clock. The reference publishes no
numbers (BASELINE.md) — the 5 s target is the baseline this framework is
judged against, so ``vs_baseline`` = target_seconds / measured_seconds
(>1 means faster than target).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Methodology: graph build + operator packing (host, numpy) and compile are
excluded; the timed region is the adaptive converge call's device compute,
synced by fetching the scalar convergence delta (over tunneled transports
``block_until_ready`` can return early, and fetching the full score vector
would time the tunnel's transfer bandwidth, not the kernel). Median of 3.
"""

import argparse
import json
import sys
import time

import numpy as np


def _fmt_peers(n: int) -> str:
    if n >= 1_000_000 and n % 1_000_000 == 0:
        return f"{n // 1_000_000}M"
    if n >= 1_000 and n % 1_000 == 0:
        return f"{n // 1_000}K"
    return str(n)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=10_000_000, help="peers")
    parser.add_argument("--m", type=int, default=8, help="BA attachment degree")
    parser.add_argument("--tol", type=float, default=1e-6)
    parser.add_argument("--alpha", type=float, default=0.1)
    parser.add_argument("--max-iters", type=int, default=500)
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args()

    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    import jax
    import jax.numpy as jnp

    from protocol_tpu.graph import barabasi_albert_edges, build_operator
    from protocol_tpu.ops.converge import converge_sparse_adaptive, operator_arrays

    t0 = time.perf_counter()
    src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
    op = build_operator(args.n, src, dst, val)
    build_s = time.perf_counter() - t0

    arrs = operator_arrays(op, dtype=jnp.float32, alpha=args.alpha)
    s0 = jnp.asarray(op.valid, dtype=jnp.float32) * 1000.0
    # move to device & compile outside the timed region
    arrs = jax.device_put(arrs)
    s0 = jax.device_put(s0)
    scores, iters, delta = converge_sparse_adaptive(
        arrs, s0, tol=args.tol, max_iterations=args.max_iters
    )
    # sync via a host transfer of the scalar delta: over tunneled TPU
    # transports, block_until_ready can return before execution finishes
    float(delta)

    times = []
    for _ in range(args.repeats):
        t1 = time.perf_counter()
        scores, iters, delta = converge_sparse_adaptive(
            arrs, s0, tol=args.tol, max_iterations=args.max_iters
        )
        float(delta)
        times.append(time.perf_counter() - t1)
    wall = float(np.median(times))

    # sanity: converged and conserved
    scores_np = np.asarray(scores)
    total = float(scores_np.sum())
    expected = op.n_valid * 1000.0
    meta = {
        "n_peers": args.n,
        "edges": int(sum(int((b != 0).sum()) for b in op.bucket_val)),
        "iterations": int(iters),
        "final_delta": float(delta),
        "converged": bool(float(delta) <= args.tol),
        "conservation_rel_err": abs(total - expected) / expected,
        "graph_build_s": round(build_s, 1),
        "device": str(jax.devices()[0]),
        "times_s": [round(t, 4) for t in times],
    }
    print(json.dumps(meta), file=sys.stderr)

    target_s = 5.0
    print(
        json.dumps(
            {
                "metric": f"{_fmt_peers(args.n)}-peer trust convergence to "
                f"{args.tol:.0e} L1 delta, wall-clock",
                "value": round(wall, 4),
                "unit": "s",
                "vs_baseline": round(target_s / wall, 3),
            }
        )
    )
    # a wall-clock for a run that never hit the advertised tolerance is not
    # a valid headline number — fail loudly (meta on stderr has the delta)
    if not meta["converged"]:
        print("BENCH FAILED: did not converge to tolerance", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
