"""Headline benchmark: large-peer trust-graph convergence on TPU.

BASELINE.json north star: converge a 10M-peer power-law trust graph to a
1e-6 relative-L1 delta in under 5 s wall-clock. The reference publishes no
numbers (BASELINE.md) — the 5 s target is the baseline this framework is
judged against, so ``vs_baseline`` = target_seconds / measured_seconds
(>1 means faster than target).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Backends: ``routed`` (default at scale) runs the Clos-routed SpMV
(ops/routed.py) — no general gathers, the sparse transpose executes as a
permutation network of lane shuffles; ``gather`` runs the bucketed-ELL
gather SpMV (ops/converge.py). The routing plan is compiled once per
graph on the host (C++ planner) and cached under ``--cache-dir`` so
repeat runs skip straight to the device phase.

Methodology: graph build, operator packing/plan compilation (host, numpy/
C++) and jit compile are excluded; the timed region is the adaptive
converge call's device compute, synced by fetching the scalar convergence
delta (over tunneled transports ``block_until_ready`` can return early,
and fetching the full score vector would time the tunnel's transfer
bandwidth, not the kernel). Median of 3.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np


def _fmt_peers(n: int) -> str:
    if n >= 1_000_000 and n % 1_000_000 == 0:
        return f"{n // 1_000_000}M"
    if n >= 1_000 and n % 1_000 == 0:
        return f"{n // 1_000}K"
    return str(n)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--ingest", action="store_true",
                        help="measure the batched attestation-ingest "
                             "kernels instead of converge (delegates to "
                             "tools/bench_ingest.py; --n = attestations)")
    parser.add_argument("--n", type=int, default=10_000_000, help="peers")
    parser.add_argument("--m", type=int, default=8, help="BA attachment degree")
    parser.add_argument("--tol", type=float, default=1e-6)
    parser.add_argument("--alpha", type=float, default=0.1)
    parser.add_argument("--max-iters", type=int, default=500)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--backend", choices=["auto", "routed", "gather"],
                        default="auto")
    parser.add_argument("--cache-dir", default="bench_cache",
                        help="routed-operator cache ('' disables)")
    parser.add_argument("--churn", action="store_true",
                        help="measure the steady-state edge-churn cost "
                             "(delta-apply per batch through "
                             "protocol_tpu.incremental) against the "
                             "full routing-plan build it replaces")
    parser.add_argument("--churn-batches", type=int, default=20)
    parser.add_argument("--churn-edges", type=int, default=500,
                        help="weight revisions per churn batch")
    parser.add_argument("--msm", action="store_true",
                        help="measure the batched multi-column commit "
                             "MSM (native.g1_msm_multi) against K "
                             "serial g1_msm calls: the K-column "
                             "aggregate-speedup curve the commit "
                             "engine rides, bit-exact per column")
    parser.add_argument("--msm-sizes", default="18,19,20",
                        help="comma-separated log2 point counts")
    parser.add_argument("--msm-cols", default="1,2,4,8",
                        help="comma-separated K values")
    parser.add_argument("--msm-reps", type=int, default=2,
                        help="repetitions per (n, K) cell (best-of)")
    parser.add_argument("--proofs", action="store_true",
                        help="measure proof-pool throughput: concurrent "
                             "clients against the ProofWorkerPool at "
                             "each worker count (proofs/hour scaling "
                             "curve, affinity hit rate, shed counters, "
                             "byte parity with the single-worker path)")
    parser.add_argument("--proof-jobs", type=int, default=16,
                        help="proofs per worker-count measurement")
    parser.add_argument("--proof-k", type=int, default=8,
                        help="synthetic circuit domain exponent")
    parser.add_argument("--proof-gates", type=int, default=48)
    parser.add_argument("--clients", type=int, default=4,
                        help="concurrent submitting clients")
    parser.add_argument("--workers-list", default="1,2",
                        help="comma-separated worker counts to sweep")
    parser.add_argument("--device-window", type=float, default=1.2,
                        help="per-proof device-occupancy window in "
                             "seconds (GIL-released wait modeling the "
                             "device-resident phase of a real prove; "
                             "see bench_proofs docstring). 0 disables")
    args = parser.parse_args()

    if args.msm:
        return bench_msm(args)

    if args.proofs:
        return bench_proofs(args)

    if args.ingest:
        # chip-measured att/s for hash + binding-checked GLV recovery;
        # 32k chunks ride far under the bisected ~408k worker-crash
        # lane ceiling (tools/probe_lane_crash.py canary).
        # NOTE: no local `import subprocess` here — a local import
        # shadows the module-level one for the WHOLE function, making
        # the non-ingest probe-and-retry path below die with
        # UnboundLocalError (exactly how the r5 battery's bench step
        # failed).
        n_att = args.n if args.n != 10_000_000 else 1 << 20
        return subprocess.call(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools", "bench_ingest.py"),
             "--n", str(n_att), "--chunk", "32768"])

    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    # the tunnel backend has failed init transiently after heavy prior
    # sessions (r5 outage note in BASELINE.md); one bounded PRE-import
    # probe-and-retry saves the round's bench row when recovery is near
    # without stalling the driver indefinitely. The probe runs in a
    # subprocess because jax caches a failed backend init for the
    # process lifetime (PTPU_BENCH_INIT_RETRIES=0 disables).
    retries = int(os.environ.get("PTPU_BENCH_INIT_RETRIES", "1"))
    if "axon" not in os.environ.get("JAX_PLATFORMS", ""):
        retries = 0  # CPU/local backends don't have the tunnel hazard
    for attempt in range(retries):
        try:
            probe_rc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, text=True,
                timeout=300).returncode
        except subprocess.TimeoutExpired:
            probe_rc = -1  # a HUNG init counts as a failed probe
        if probe_rc == 0:
            break
        print("bench: backend init probe failed; retrying in 240s",
              file=sys.stderr, flush=True)
        time.sleep(240)

    import jax
    import jax.numpy as jnp

    from protocol_tpu.graph import barabasi_albert_edges, build_operator
    from protocol_tpu.ops.converge import converge_sparse_adaptive, operator_arrays
    from protocol_tpu.ops.routed import (
        RoutedOperator,
        build_routed_operator,
        converge_routed_adaptive,
        routed_arrays,
    )

    backend = args.backend
    if backend == "auto":
        # the routed path wins beyond ~100K peers; below that the plan
        # compilation outweighs the per-iteration gather savings
        backend = "routed" if args.n >= 100_000 else "gather"
    if backend == "routed":
        # the pure-Python planner fallback is per-edge host work —
        # without the native planner, large routed builds take hours
        from protocol_tpu import native as pn

        if not pn.available():
            print("bench: native Clos planner unavailable; "
                  "falling back to gather backend", file=sys.stderr)
            backend = "gather"

    if args.churn:
        return bench_churn(args)

    t0 = time.perf_counter()
    rop = None
    cache_path = None
    if backend == "routed" and args.cache_dir:
        # raw-directory cache (fast loads); migrate a legacy .npz once
        cache_path = (Path(args.cache_dir)
                      / f"routed_ba_n{args.n}_m{args.m}_s0_v2")
        legacy = (Path(args.cache_dir)
                  / f"routed_ba_n{args.n}_m{args.m}_s0_v1.npz")
        if cache_path.exists():
            rop = RoutedOperator.load(cache_path)
        elif legacy.exists():
            rop = RoutedOperator.load(legacy)
            rop.save(cache_path)
            # migration complete — don't double the cache (idempotent
            # for concurrent runs)
            legacy.unlink(missing_ok=True)

    if backend == "routed":
        if rop is None:
            src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
            rop = build_routed_operator(args.n, src, dst, val)
            if cache_path is not None:
                cache_path.parent.mkdir(parents=True, exist_ok=True)
                rop.save(cache_path)
        build_s = time.perf_counter() - t0
        arrs, static = routed_arrays(rop, dtype=jnp.float32, alpha=args.alpha)
        arrs = jax.device_put(arrs)
        s0 = jax.device_put(jnp.asarray(rop.initial_scores(1000.0)))
        n_valid = rop.n_valid
        nnz = rop.nnz

        def run():
            return converge_routed_adaptive(
                arrs, static, s0, tol=args.tol, max_iterations=args.max_iters
            )

        def final_total(scores):
            return float(rop.scores_for_nodes(np.asarray(scores)).sum())
    else:
        src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
        op = build_operator(args.n, src, dst, val)
        build_s = time.perf_counter() - t0
        arrs = jax.device_put(operator_arrays(op, dtype=jnp.float32,
                                              alpha=args.alpha))
        s0 = jax.device_put(jnp.asarray(op.valid, dtype=jnp.float32) * 1000.0)
        n_valid = op.n_valid
        nnz = int(sum(int((b != 0).sum()) for b in op.bucket_val))

        def run():
            return converge_sparse_adaptive(
                arrs, s0, tol=args.tol, max_iterations=args.max_iters
            )

        def final_total(scores):
            return float(np.asarray(scores).sum())

    # compile outside the timed region; sync via a host transfer of the
    # scalar delta (over tunneled TPU transports, block_until_ready can
    # return before execution finishes)
    scores, iters, delta = run()
    float(delta)

    times = []
    for _ in range(args.repeats):
        t1 = time.perf_counter()
        scores, iters, delta = run()
        float(delta)
        times.append(time.perf_counter() - t1)
    wall = float(np.median(times))

    total = final_total(scores)
    expected = n_valid * 1000.0
    meta = {
        "backend": backend,
        "n_peers": args.n,
        "edges": nnz,
        "iterations": int(iters),
        "final_delta": float(delta),
        "converged": bool(float(delta) <= args.tol),
        "conservation_rel_err": abs(total - expected) / expected,
        "build_s": round(build_s, 1),
        "device": str(jax.devices()[0]),
        "times_s": [round(t, 4) for t in times],
    }
    print(json.dumps(meta), file=sys.stderr)

    target_s = 5.0
    print(
        json.dumps(
            {
                "metric": f"{_fmt_peers(args.n)}-peer trust convergence to "
                f"{args.tol:.0e} L1 delta, wall-clock",
                "value": round(wall, 4),
                "unit": "s",
                "vs_baseline": round(target_s / wall, 3),
            }
        )
    )
    # a wall-clock for a run that never hit the advertised tolerance is not
    # a valid headline number — fail loudly (meta on stderr has the delta)
    if not meta["converged"]:
        print("BENCH FAILED: did not converge to tolerance", file=sys.stderr)
        return 1
    return 0


def bench_msm(args) -> int:
    """K-column commit-MSM batching: ``native.g1_msm_multi`` (the
    commit engine's kernel — base parse/Montgomery conversion amortized
    over all K columns, on-the-fly signed recode, bucket-range-tiled
    batch-affine levels, 32-chain IFMA bucket reduction) against K
    serial ``native.g1_msm`` calls (the
    committed-baseline Pippenger, BASELINE.md r4's 3.9 s at 2^20 —
    kept untouched as the oracle). Single-threaded, same box, same
    ``PN_MSM_C``/auto-tune state for both sides; every column is
    asserted bit-exact against its serial oracle before timing counts.

    Headline ``value`` = aggregate speedup at the largest size's K=4
    cell (serial wall / multi wall); ``vs_baseline`` = value / 1.5,
    the BENCH_r08 acceptance floor (>1 means the batching beat it)."""
    import random

    from protocol_tpu import native
    from protocol_tpu.utils.fields import BN254_FR_MODULUS as FR
    from protocol_tpu.zk.bn254 import BN254_FQ_MODULUS as FQ, G1_GEN

    if not native.available():
        print("BENCH FAILED: native library unavailable", file=sys.stderr)
        return 1
    sizes = [int(x) for x in args.msm_sizes.split(",") if x]
    cols = [int(x) for x in args.msm_cols.split(",") if x]
    kmax = max(cols)
    rng = random.Random(0xB08)
    nmax = 1 << max(sizes)
    t0 = time.perf_counter()
    seed_sc = native.ints_to_limbs(
        [rng.randrange(1, FR) for _ in range(nmax)])
    bases_all = native.g1_fixed_base_muls(FQ, G1_GEN, seed_sc)
    cols_all = np.stack([
        native.ints_to_limbs([rng.randrange(0, FR) for _ in range(nmax)])
        for _ in range(kmax)])
    fixture_s = time.perf_counter() - t0

    curve = []
    for logn in sizes:
        n = 1 << logn
        bases = np.ascontiguousarray(bases_all[:n])
        for kcols in cols:
            scal = np.ascontiguousarray(cols_all[:kcols, :n])
            serial_s = multi_s = None
            serial_pts = multi_pts = None
            for _ in range(max(1, args.msm_reps)):
                t0 = time.perf_counter()
                serial_pts = [native.g1_msm(FQ, bases, scal[k])
                              for k in range(kcols)]
                dt = time.perf_counter() - t0
                serial_s = dt if serial_s is None else min(serial_s, dt)
                t0 = time.perf_counter()
                multi_pts = native.g1_msm_multi(FQ, bases, scal)
                dt = time.perf_counter() - t0
                multi_s = dt if multi_s is None else min(multi_s, dt)
            if multi_pts != serial_pts:
                print(f"BENCH FAILED: column mismatch at n=2^{logn} "
                      f"K={kcols}", file=sys.stderr)
                return 1
            cell = {"log2_n": logn, "k_columns": kcols,
                    "serial_s": round(serial_s, 3),
                    "multi_s": round(multi_s, 3),
                    "aggregate_speedup": round(serial_s / multi_s, 3)}
            curve.append(cell)
            print(json.dumps(cell), file=sys.stderr)

    headline_k = 4 if 4 in cols else kmax
    top = next(c for c in curve
               if c["log2_n"] == max(sizes)
               and c["k_columns"] == headline_k)
    meta = {
        "mode": "msm",
        "curve": curve,
        "fixture_s": round(fixture_s, 1),
        "pn_msm_c": os.environ.get("PN_MSM_C"),
        "host_cores": os.cpu_count(),
        "bit_exact": "every multi column compared == its serial "
                     "g1_msm oracle before timing counts",
        "methodology": "single thread, one box, best-of-reps per cell "
                       "for BOTH sides; serial side is the committed-"
                       "baseline g1_msm (untouched by this round); "
                       "multi side is g1_msm_multi — base parse + "
                       "Montgomery/w-domain conversion amortized over "
                       "all K columns, on-the-fly signed recode, "
                       "bucket-range-tiled batch-affine levels, "
                       "32-chain IFMA bucket reduction; cross-column "
                       "sharing INSIDE one window pass measured net-"
                       "negative on this box (cache/TLB), so the "
                       "default sweeps one column per pass "
                       "(PN_MSM_KB re-enables wider sharing)",
    }
    print(json.dumps(meta), file=sys.stderr)
    value = top["aggregate_speedup"]
    print(json.dumps({
        "metric": f"batched {headline_k}-column commit MSM at "
                  f"2^{max(sizes)}, aggregate speedup vs "
                  f"{headline_k} serial g1_msm calls",
        "value": value,
        "unit": "x",
        "vs_baseline": round(value / 1.5, 3),
    }))
    if value < 1.5:
        print("BENCH FAILED: aggregate speedup under the 1.5x floor",
              file=sys.stderr)
        return 1
    return 0


def bench_churn(args) -> int:
    """Steady-state churn cost: with a DeltaEngine anchored on one full
    routed build, a batch of weight revisions costs O(batch) host work
    plus O(dirty) device scatters — measured here against the full
    plan build the pre-PR 6 write path would have paid per change.
    ``vs_baseline`` = full_build_s / delta_apply_s (>1 means a churn
    window is cheaper than the rebuild it replaces)."""
    import jax

    from protocol_tpu.graph import barabasi_albert_edges, filter_edges
    from protocol_tpu.incremental import DeltaEngine, revision_batch
    from protocol_tpu.ops.routed import build_routed_operator

    rng = np.random.default_rng(7)
    src, dst, val = barabasi_albert_edges(args.n, args.m, seed=0)
    valid = np.ones(args.n, dtype=bool)
    fsrc, fdst, _, _, _, raw, _ = filter_edges(
        args.n, src, dst, val, valid, return_raw=True)
    cur = raw.copy()

    t0 = time.perf_counter()
    rop = build_routed_operator(args.n, src, dst, val, valid)
    build_s = time.perf_counter() - t0

    eng = DeltaEngine.anchor(args.n, src, dst, val, valid, rop)
    # one converge to settle jit caches; churn timing is host+scatter
    scores, iters, delta = eng.converge(
        eng.initial_node_scores(1000.0), args.max_iters, args.tol)

    apply_s = []
    for _ in range(args.churn_batches):
        deltas = revision_batch(rng, fsrc, fdst, cur, args.churn_edges)
        t1 = time.perf_counter()
        if not eng.apply_deltas(deltas):
            print("BENCH FAILED: delta batch rejected", file=sys.stderr)
            return 1
        apply_s.append(time.perf_counter() - t1)
    wall = float(np.median(apply_s))

    meta = {
        "mode": "churn",
        "n_peers": args.n,
        "edges": len(fsrc),
        "batch_edges": args.churn_edges,
        "batches": args.churn_batches,
        "full_build_s": round(build_s, 3),
        "delta_apply_s": [round(t, 5) for t in apply_s],
        "converge_iterations": int(iters),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(meta), file=sys.stderr)
    print(json.dumps({
        "metric": f"{_fmt_peers(args.n)}-peer steady churn: delta-apply "
                  f"per {args.churn_edges}-revision batch "
                  f"(vs full plan rebuild)",
        "value": round(wall, 5),
        "unit": "s",
        "vs_baseline": round(build_s / wall, 1),
    }))
    return 0


def bench_proofs(args) -> int:
    """Proof-pool throughput: concurrent clients vs worker count.

    Each job is a REAL host-path prove (``prove_fast``, deterministic
    blinding — byte parity with the pre-pool single-worker output is
    asserted before anything is timed) of a smoke-scale circuit,
    wrapped in a ``--device-window`` seconds device-occupancy window:
    ``time.sleep`` releasing the GIL, standing in for the
    device-resident phase of a production prove (the r5 battery's
    k=20/21 proves spend minutes blocked on device compute per second
    of host orchestration). On a multi-device box each worker's window
    runs on its own chip; on this host-path box the window is what
    makes per-worker overlap physically possible at all — a 1-core
    container cannot overlap host arithmetic, so with ``--device-window
    0`` the curve measures scheduling overhead only (expect ~1.0x; the
    measured flat host-only number is reported in the meta either way).

    Two job kinds run two distinct circuits, so the affinity scheduler
    has real cache keys to route on; clients retry 429 sheds, so the
    shed counters exercise the tiered admission path under the burst.

    Headline: proofs/hour at each worker count; ``value`` = the
    2-worker scaling factor (2 workers vs 1), ``vs_baseline`` =
    value / 1.8 — the BENCH_r07 acceptance floor (>1 means the pool
    beat it).
    """
    import threading

    from protocol_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from protocol_tpu.cli.profilecmd import synthetic_circuit
    from protocol_tpu.service.faults import FaultInjector
    from protocol_tpu.service.pool import ProofWorkerPool, QueueFullError
    from protocol_tpu.service.provers import PROOF_PRIORITIES
    from protocol_tpu.utils.errors import EigenError
    from protocol_tpu.zk import prover_fast as pf

    params = pf.setup_params_fast(args.proof_k, seed=b"pool-bench")
    kinds = {}
    references = {}
    for kind, seed in (("eigentrust", 11), ("threshold", 12)):
        cs = synthetic_circuit(gates=args.proof_gates, seed=seed)
        pk = pf.keygen_fast(params, cs)
        kinds[kind] = (pk, cs)
        references[kind] = pf.prove_fast(params, pk, cs,
                                         randint=lambda: 424242)

    window = max(0.0, args.device_window)

    def make_prover(kind):
        pk, cs = kinds[kind]

        def prove(p):
            proof = pf.prove_fast(params, pk, cs,
                                  randint=lambda: 424242)
            if window:
                time.sleep(window)  # the device-occupancy stand-in
            return {"proof": proof.hex()}

        return prove

    registry = {k: make_prover(k) for k in kinds}
    # tier-0 kind: instant, shed FIRST once the queue passes the
    # watermark — the burst below proves the tiered admission path
    registry["profile"] = lambda p: {"ok": True}
    no_faults = FaultInjector({"rpc": 0.0, "device": 0.0, "disk": 0.0})

    def run_pool(n_workers: int, n_jobs: int):
        pool = ProofWorkerPool(
            registry, capacity=8, workers=n_workers, faults=no_faults,
            priorities=PROOF_PRIORITIES,
            worker_env=lambda w: pf.worker_isolation(w.name, w.device))
        pool.start()
        ids: list = []
        ids_lock = threading.Lock()

        def client(c):
            got = []
            for i in range(n_jobs // args.clients):
                kind = "eigentrust" if (c + i) % 2 else "threshold"
                while True:
                    try:
                        got.append(pool.submit(kind, {}).job_id)
                        break
                    except QueueFullError:
                        time.sleep(0.02)  # shed: retry like a client
                    except EigenError:
                        time.sleep(0.05)  # byte ceiling: back off
            with ids_lock:
                ids.extend(got)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        # tier-0 burst against the deep queue: profile jobs shed with
        # 429 while the proof kinds keep landing — the graduated floor
        time.sleep(0.3)
        profile_shed = 0
        for _ in range(4):
            try:
                pool.submit("profile", {})
            except QueueFullError:
                profile_shed += 1
        for t in threads:
            t.join()
        # a stalled pool (the scheduling regression this benchmark
        # exists to catch) must FAIL the bench, not hang it
        stall_deadline = time.monotonic() + 600.0
        while not all(pool.get(j).status in ("done", "failed")
                      for j in ids):
            if time.monotonic() > stall_deadline:
                raise RuntimeError("proof pool stalled (jobs never "
                                   "reached a terminal state)")
            time.sleep(0.01)
        wall = time.perf_counter() - t0
        # byte parity: every pool proof matches the single-worker
        # reference for its kind
        for jid in ids:
            job = pool.get(jid)
            assert job.status == "done", (jid, job.error)
            assert bytes.fromhex(job.result["proof"]) \
                == references[job.kind], f"{jid}: proof bytes diverged"
        status = pool.pool_status()
        pool.drain(10.0)
        hits = sum(w["affinity_hits"] for w in status["workers"])
        misses = sum(w["affinity_misses"] for w in status["workers"])
        return {
            "workers": n_workers,
            "jobs": len(ids),
            "wall_s": round(wall, 3),
            "proofs_per_hour": round(len(ids) / wall * 3600.0, 1),
            "affinity_hit_rate": round(hits / max(hits + misses, 1), 3),
            "stolen": sum(w["stolen"] for w in status["workers"]),
            "shed": status["shed"],
            "profile_burst_shed_429": profile_shed,
            "per_worker_jobs": {w["worker"]: w["jobs_run"]
                                for w in status["workers"]},
        }

    worker_counts = [int(x) for x in args.workers_list.split(",") if x]
    # warm the prover caches/jit before timing
    run_pool(1, max(args.clients, 4))
    curve = [run_pool(nw, args.proof_jobs) for nw in worker_counts]

    by_workers = {c["workers"]: c for c in curve}
    speedup_2w = None
    if 2 in by_workers and 1 in by_workers:
        speedup_2w = (by_workers[2]["proofs_per_hour"]
                      / by_workers[1]["proofs_per_hour"])
    meta = {
        "mode": "proofs",
        "k": args.proof_k,
        "gates": args.proof_gates,
        "clients": args.clients,
        "device_window_s": window,
        "curve": curve,
        "byte_parity": "identical to single-worker prove_fast output",
        "host_cores": os.cpu_count(),
        "speedup_2w": (round(speedup_2w, 3)
                       if speedup_2w is not None else None),
    }
    print(json.dumps(meta), file=sys.stderr)
    value = speedup_2w if speedup_2w is not None else 1.0
    print(json.dumps({
        "metric": "proof pool proofs/hour scaling, 2 workers vs 1 "
                  f"(host path, k={args.proof_k} circuits, "
                  f"{window:.2f}s device window)",
        "value": round(value, 3),
        "unit": "x",
        "vs_baseline": round(value / 1.8, 3),
    }))
    if speedup_2w is not None and speedup_2w < 1.8:
        print("BENCH FAILED: 2-worker scaling under the 1.8x floor",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
