// Native prover core: 256-bit Montgomery field arithmetic, radix-2 NTT,
// Pippenger G1 MSM, grand products and the quotient kernel for the
// framework's PLONK protocol.
//
// The reference's entire proving stack is native (Rust halo2,
// eigentrust-zk/Cargo.toml); this library is the framework's equivalent
// performance layer. Python keeps witness generation and protocol
// orchestration (zk/prover_fast.py); every O(n)/O(n log n) polynomial or
// curve operation crosses this boundary as flat little-endian 4x64-bit
// limb arrays in standard (non-Montgomery) form.
//
// Build: g++ -O3 -shared -fPIC -o libprotocol_native.so protocol_native.cpp
// (driven by protocol_tpu/native/__init__.py, which caches the .so).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#ifdef __linux__
#include <sys/mman.h>
#endif
#ifdef __linux__
#include <sched.h>
#endif
#include <cstdint>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

typedef unsigned __int128 u128;
typedef uint64_t u64;

struct Fp {
    u64 v[4];
};

// Field context: modulus, -modulus^-1 mod 2^64, R^2 mod p (Montgomery).
struct FieldCtx {
    Fp mod;
    u64 inv;   // -p^{-1} mod 2^64
    Fp r2;     // (2^256)^2 mod p
    Fp one;    // 2^256 mod p (Montgomery 1)
};

static inline bool geq(const Fp &a, const Fp &b) {
    for (int i = 3; i >= 0; --i) {
        if (a.v[i] != b.v[i]) return a.v[i] > b.v[i];
    }
    return true;
}

static inline void sub_nored(Fp &out, const Fp &a, const Fp &b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)a.v[i] - b.v[i] - (u64)borrow;
        out.v[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static inline void add_mod(Fp &out, const Fp &a, const Fp &b, const FieldCtx &f) {
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
        u128 s = (u128)a.v[i] + b.v[i] + (u64)carry;
        out.v[i] = (u64)s;
        carry = s >> 64;
    }
    if (carry || geq(out, f.mod)) {
        Fp t;
        sub_nored(t, out, f.mod);
        out = t;
    }
}

static inline void sub_mod(Fp &out, const Fp &a, const Fp &b, const FieldCtx &f) {
    u128 borrow = 0;
    Fp t;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)a.v[i] - b.v[i] - (u64)borrow;
        t.v[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    if (borrow) {
        u128 carry = 0;
        for (int i = 0; i < 4; ++i) {
            u128 s = (u128)t.v[i] + f.mod.v[i] + (u64)carry;
            t.v[i] = (u64)s;
            carry = s >> 64;
        }
    }
    out = t;
}

// CIOS Montgomery multiplication.
static inline void mont_mul(Fp &out, const Fp &a, const Fp &b, const FieldCtx &f) {
    u64 t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
        u128 c = 0;
        for (int j = 0; j < 4; ++j) {
            u128 s = (u128)t[j] + (u128)a.v[i] * b.v[j] + (u64)c;
            t[j] = (u64)s;
            c = s >> 64;
        }
        u128 s = (u128)t[4] + (u64)c;
        t[4] = (u64)s;
        t[5] = (u64)(s >> 64);

        u64 m = t[0] * f.inv;
        c = ((u128)t[0] + (u128)m * f.mod.v[0]) >> 64;
        for (int j = 1; j < 4; ++j) {
            u128 s2 = (u128)t[j] + (u128)m * f.mod.v[j] + (u64)c;
            t[j - 1] = (u64)s2;
            c = s2 >> 64;
        }
        u128 s2 = (u128)t[4] + (u64)c;
        t[3] = (u64)s2;
        t[4] = t[5] + (u64)(s2 >> 64);
        t[5] = 0;
    }
    Fp r = {{t[0], t[1], t[2], t[3]}};
    if (t[4] || geq(r, f.mod)) {
        Fp q;
        sub_nored(q, r, f.mod);
        // note: if t[4] set, the true value is r + 2^256 which is < 2p,
        // so one subtraction (mod 2^256 arithmetic) lands in range
        out = q;
    } else {
        out = r;
    }
}

static inline void to_mont(Fp &out, const Fp &a, const FieldCtx &f) {
    mont_mul(out, a, f.r2, f);
}

static inline void from_mont(Fp &out, const Fp &a, const FieldCtx &f) {
    Fp one = {{1, 0, 0, 0}};
    mont_mul(out, a, one, f);
}

static inline void mont_sqr(Fp &out, const Fp &a, const FieldCtx &f) {
    mont_mul(out, a, a, f);
}

static void mont_pow(Fp &out, const Fp &base, const u64 *exp, int exp_words,
                     const FieldCtx &f) {
    Fp acc = f.one;
    Fp b = base;
    for (int w = 0; w < exp_words; ++w) {
        u64 e = exp[w];
        for (int bit = 0; bit < 64; ++bit) {
            if (e & 1) mont_mul(acc, acc, b, f);
            mont_sqr(b, b, f);
            e >>= 1;
        }
    }
    out = acc;
}

static void mont_inv(Fp &out, const Fp &a, const FieldCtx &f) {
    // a^(p-2)
    u64 e[4];
    Fp pm2 = f.mod;
    u128 borrow = 2;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)pm2.v[i] - (u64)borrow;
        e[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    mont_pow(out, a, e, 4, f);
}

// --------------------------------------------------------------------------
// context setup

static FieldCtx make_ctx(const u64 *mod_limbs) {
    FieldCtx f;
    std::memcpy(f.mod.v, mod_limbs, 32);
    // inv = -p^{-1} mod 2^64 (Newton iteration)
    u64 p0 = f.mod.v[0];
    u64 inv = 1;
    for (int i = 0; i < 63; ++i) inv *= 2 - p0 * inv;
    f.inv = ~inv + 1;
    // one = 2^256 mod p: compute by repeated doubling of 1 (256 times)
    Fp one = {{1, 0, 0, 0}};
    Fp acc = one;
    for (int i = 0; i < 256; ++i) add_mod(acc, acc, acc, f);
    f.one = acc;
    // r2 = (2^256)^2 mod p: double 'one' 256 more times
    Fp r2 = acc;
    for (int i = 0; i < 256; ++i) add_mod(r2, r2, r2, f);
    f.r2 = r2;
    return f;
}

extern "C" {

// --- field vector ops (standard-form in/out) ------------------------------

void fr_vec_op(const u64 *mod_limbs, int op, u64 *out, const u64 *a,
               const u64 *b, long n) {
    FieldCtx f = make_ctx(mod_limbs);
    for (long i = 0; i < n; ++i) {
        Fp x, y, r;
        std::memcpy(x.v, a + 4 * i, 32);
        if (b) std::memcpy(y.v, b + 4 * i, 32);
        switch (op) {
        case 0: add_mod(r, x, y, f); break;
        case 1: sub_mod(r, x, y, f); break;
        case 2: {  // mul
            Fp xm, ym;
            to_mont(xm, x, f);
            to_mont(ym, y, f);
            mont_mul(r, xm, ym, f);
            from_mont(r, r, f);
            break;
        }
        default: r = x;
        }
        std::memcpy(out + 4 * i, r.v, 32);
    }
}

// scalar-broadcast variants: b points at ONE field element.
// op 0 add, 1 sub (a - s), 2 mul.
void fr_vec_scalar_op(const u64 *mod_limbs, int op, u64 *out, const u64 *a,
                      const u64 *scalar, long n) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp s, sm;
    std::memcpy(s.v, scalar, 32);
    to_mont(sm, s, f);
    for (long i = 0; i < n; ++i) {
        Fp x, r;
        std::memcpy(x.v, a + 4 * i, 32);
        switch (op) {
        case 0: add_mod(r, x, s, f); break;
        case 1: sub_mod(r, x, s, f); break;
        case 2: {
            Fp xm;
            to_mont(xm, x, f);
            mont_mul(r, xm, sm, f);
            from_mont(r, r, f);
            break;
        }
        default: r = x;
        }
        std::memcpy(out + 4 * i, r.v, 32);
    }
}

// out[i] = acc after synthetic division: (f(X) - f(z)) / (X - z).
// coeffs: n low-first; out: n-1 coefficients.
void fr_poly_divide_linear(const u64 *mod_limbs, const u64 *coeffs, long n,
                           const u64 *z_limbs, u64 *out) {
    FieldCtx f = make_ctx(mod_limbs);
    if (n <= 1) return;
    Fp z;
    std::memcpy(z.v, z_limbs, 32);
    to_mont(z, z, f);
    Fp acc = {{0, 0, 0, 0}};
    for (long i = n - 1; i >= 1; --i) {
        Fp c, t;
        std::memcpy(c.v, coeffs + 4 * i, 32);
        to_mont(c, c, f);
        mont_mul(t, acc, z, f);
        add_mod(acc, t, c, f);
        from_mont(t, acc, f);
        std::memcpy(out + 4 * (i - 1), t.v, 32);
    }
}

// --- NTT ------------------------------------------------------------------

// in-place radix-2 DIT NTT over the subgroup generated by omega (standard
// form in/out). dir=0 forward, dir=1 inverse (uses omega^-1 and scales by
// n^-1).
// radix-2 NTT on a Montgomery-form array in place (internal helper).
// ``tw_ready`` marks the twiddle table as already built for this
// (omega, n) — the four-step path reuses one table across all rows of a
// stage instead of rebuilding it per row.
static void ntt_core(Fp *a, long n, const Fp &omega, const FieldCtx &f,
                     std::vector<Fp> &tw, bool tw_ready = false) {
    // bit reversal
    for (long i = 1, j = 0; i < n; ++i) {
        long bit = n >> 1;
        for (; j & bit; bit >>= 1) j ^= bit;
        j ^= bit;
        if (i < j) std::swap(a[i], a[j]);
    }
    if (!tw_ready) {
        if ((long)tw.size() < n / 2) tw.resize(n / 2 > 0 ? n / 2 : 1);
        tw[0] = f.one;
        for (long j = 1; j < n / 2; ++j) mont_mul(tw[j], tw[j - 1], omega, f);
    }
    for (long len = 2; len <= n; len <<= 1) {
        long stride = n / len;
        for (long i = 0; i < n; i += len) {
            for (long j = 0; j < len / 2; ++j) {
                Fp u = a[i + j];
                Fp v;
                mont_mul(v, a[i + j + len / 2], tw[j * stride], f);
                add_mod(a[i + j], u, v, f);
                sub_mod(a[i + j + len / 2], u, v, f);
            }
        }
    }
}

// blocked out-of-place transpose of an A x B Fp matrix
static void fp_transpose(const Fp *src, Fp *dst, long rows, long cols) {
    const long BLK = 32;
    for (long i0 = 0; i0 < rows; i0 += BLK)
        for (long j0 = 0; j0 < cols; j0 += BLK) {
            long i1 = std::min(i0 + BLK, rows), j1 = std::min(j0 + BLK, cols);
            for (long i = i0; i < i1; ++i)
                for (long j = j0; j < j1; ++j)
                    dst[j * rows + i] = src[i * cols + j];
        }
}

void ntt(const u64 *mod_limbs, u64 *data, long n, const u64 *omega_limbs,
         int dir) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp omega_s;
    std::memcpy(omega_s.v, omega_limbs, 32);
    Fp omega;
    to_mont(omega, omega_s, f);
    if (dir) mont_inv(omega, omega, f);

    std::vector<Fp> a(n);
    for (long i = 0; i < n; ++i) {
        Fp x;
        std::memcpy(x.v, data + 4 * i, 32);
        to_mont(a[i], x, f);
    }

    std::vector<Fp> tw;
    if (n <= (1 << 14)) {
        ntt_core(a.data(), n, omega, f, tw);
    } else {
        // cache-blocked four-step: n = A·B, x[j1·B + j2];
        //   X[k1 + k2·A] = Σ_{j2} ω^{A j2 k2} · ( ω^{j2 k1} ·
        //                  Σ_{j1} ω^{B j1 k1} x[j1·B + j2] )
        // inner/outer NTTs are length-A/B rows that fit in cache, the
        // cross-stage twiddle is one running-product multiply per
        // element, and data movement is three blocked transposes.
        int lg = 0;
        while ((1L << lg) < n) ++lg;
        long A = 1L << (lg / 2), B = n / A;
        Fp omega_A, omega_B;
        u64 expB[1] = {(u64)B}, expA[1] = {(u64)A};
        mont_pow(omega_A, omega, expB, 1, f);  // ω^B (order A)
        mont_pow(omega_B, omega, expA, 1, f);  // ω^A (order B)
        std::vector<Fp> t(n);
        // transpose to (B rows of A): t[j2][j1]
        fp_transpose(a.data(), t.data(), A, B);
        // inner A-point NTTs along rows of t, then the cross twiddle:
        // t[j2][k1] *= ω^{j2·k1} via a per-row running power of ω^{j2}
        std::vector<Fp> wrow(B);
        wrow[0] = f.one;
        for (long j2 = 1; j2 < B; ++j2) mont_mul(wrow[j2], wrow[j2 - 1], omega, f);
        for (long j2 = 0; j2 < B; ++j2) {
            Fp *row = &t[j2 * A];
            ntt_core(row, A, omega_A, f, tw, j2 > 0);
            Fp w = wrow[j2], pw = w;
            for (long k1 = 1; k1 < A; ++k1) {
                mont_mul(row[k1], row[k1], pw, f);
                mont_mul(pw, pw, w, f);
            }
        }
        // transpose to (A rows of B): u[k1][j2], outer B-point NTTs
        fp_transpose(t.data(), a.data(), B, A);
        for (long k1 = 0; k1 < A; ++k1)
            ntt_core(&a[k1 * B], B, omega_B, f, tw, k1 > 0);
        // a[k1][k2] holds X[k1 + k2·A]; natural order = transpose
        fp_transpose(a.data(), t.data(), A, B);
        a.swap(t);
    }

    if (dir) {
        // scale by n^{-1}
        Fp n_fp = {{(u64)n, 0, 0, 0}};
        Fp n_mont, n_inv;
        to_mont(n_mont, n_fp, f);
        mont_inv(n_inv, n_mont, f);
        for (long i = 0; i < n; ++i) mont_mul(a[i], a[i], n_inv, f);
    }
    for (long i = 0; i < n; ++i) {
        Fp x;
        from_mont(x, a[i], f);
        std::memcpy(data + 4 * i, x.v, 32);
    }
}

// multiply data[i] by shift^i (coset scaling), standard form
void coset_scale(const u64 *mod_limbs, u64 *data, long n,
                 const u64 *shift_limbs, int invert) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp s;
    std::memcpy(s.v, shift_limbs, 32);
    to_mont(s, s, f);
    if (invert) mont_inv(s, s, f);
    Fp acc = f.one;
    for (long i = 0; i < n; ++i) {
        Fp x;
        std::memcpy(x.v, data + 4 * i, 32);
        to_mont(x, x, f);
        mont_mul(x, x, acc, f);
        from_mont(x, x, f);
        std::memcpy(data + 4 * i, x.v, 32);
        mont_mul(acc, acc, s, f);
    }
}

// Horner evaluation of many polynomials (coeff-major: polys[p][i]) at x.
void poly_eval_many(const u64 *mod_limbs, const u64 *coeffs, long n_polys,
                    long n, const u64 *x_limbs, u64 *out) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp x;
    std::memcpy(x.v, x_limbs, 32);
    to_mont(x, x, f);
    for (long p = 0; p < n_polys; ++p) {
        Fp acc = {{0, 0, 0, 0}};
        const u64 *c = coeffs + p * 4 * n;
        for (long i = n - 1; i >= 0; --i) {
            Fp ci;
            std::memcpy(ci.v, c + 4 * i, 32);
            to_mont(ci, ci, f);
            mont_mul(acc, acc, x, f);
            add_mod(acc, acc, ci, f);
        }
        from_mont(acc, acc, f);
        std::memcpy(out + 4 * p, acc.v, 32);
    }
}

// batch inversion, standard form; zeros stay zero
void batch_inverse(const u64 *mod_limbs, u64 *data, long n) {
    FieldCtx f = make_ctx(mod_limbs);
    std::vector<Fp> vals(n), prefix(n);
    Fp acc = f.one;
    for (long i = 0; i < n; ++i) {
        Fp x;
        std::memcpy(x.v, data + 4 * i, 32);
        to_mont(vals[i], x, f);
        prefix[i] = acc;
        bool zero = !(x.v[0] | x.v[1] | x.v[2] | x.v[3]);
        if (!zero) mont_mul(acc, acc, vals[i], f);
    }
    Fp inv;
    mont_inv(inv, acc, f);
    for (long i = n - 1; i >= 0; --i) {
        Fp x = vals[i];
        bool zero = true;
        for (int k = 0; k < 4; ++k) zero = zero && !x.v[k];
        if (zero) continue;
        Fp r;
        mont_mul(r, inv, prefix[i], f);
        mont_mul(inv, inv, x, f);
        from_mont(r, r, f);
        std::memcpy(data + 4 * i, r.v, 32);
    }
}

// --- G1 (short Weierstrass y^2 = x^3 + b, a=0) ----------------------------

struct JacPoint {
    Fp x, y, z;  // Montgomery form; z=0 => identity
};

static inline bool is_zero_fp(const Fp &a) {
    return !(a.v[0] | a.v[1] | a.v[2] | a.v[3]);
}

static void jac_double(JacPoint &r, const JacPoint &p_in, const FieldCtx &f) {
    JacPoint p = p_in;  // r may alias p_in
    if (is_zero_fp(p.z)) { r = p; return; }
    Fp a, bb, c, d, e, g, t;
    mont_sqr(a, p.x, f);                 // A = X^2
    mont_sqr(bb, p.y, f);                // B = Y^2
    mont_sqr(c, bb, f);                  // C = B^2
    add_mod(d, p.x, bb, f);              // (X+B)
    mont_sqr(d, d, f);                   // (X+B)^2
    sub_mod(d, d, a, f);
    sub_mod(d, d, c, f);
    add_mod(d, d, d, f);                 // D = 2((X+B)^2 - A - C)
    add_mod(e, a, a, f);
    add_mod(e, e, a, f);                 // E = 3A
    mont_sqr(g, e, f);                   // G = E^2
    sub_mod(r.x, g, d, f);
    sub_mod(r.x, r.x, d, f);             // X' = G - 2D
    sub_mod(t, d, r.x, f);
    mont_mul(t, t, e, f);
    Fp c8;
    add_mod(c8, c, c, f);
    add_mod(c8, c8, c8, f);
    add_mod(c8, c8, c8, f);              // 8C
    sub_mod(r.y, t, c8, f);              // Y' = E(D - X') - 8C
    mont_mul(r.z, p.y, p.z, f);
    add_mod(r.z, r.z, r.z, f);           // Z' = 2YZ
}

static void jac_add(JacPoint &r, const JacPoint &p_in, const JacPoint &q_in,
                    const FieldCtx &f) {
    JacPoint p = p_in, q = q_in;  // r may alias either input
    if (is_zero_fp(p.z)) { r = q; return; }
    if (is_zero_fp(q.z)) { r = p; return; }
    Fp z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t;
    mont_sqr(z1z1, p.z, f);
    mont_sqr(z2z2, q.z, f);
    mont_mul(u1, p.x, z2z2, f);
    mont_mul(u2, q.x, z1z1, f);
    mont_mul(s1, p.y, q.z, f);
    mont_mul(s1, s1, z2z2, f);
    mont_mul(s2, q.y, p.z, f);
    mont_mul(s2, s2, z1z1, f);
    sub_mod(h, u2, u1, f);
    sub_mod(rr, s2, s1, f);
    if (is_zero_fp(h)) {
        if (is_zero_fp(rr)) { jac_double(r, p, f); return; }
        r.x = f.one; r.y = f.one;
        r.z = Fp{{0, 0, 0, 0}};
        return;
    }
    add_mod(rr, rr, rr, f);              // r = 2(S2-S1)
    add_mod(i, h, h, f);
    mont_sqr(i, i, f);                   // I = (2H)^2
    mont_mul(j, h, i, f);                // J = H*I
    mont_mul(v, u1, i, f);               // V = U1*I
    mont_sqr(r.x, rr, f);
    sub_mod(r.x, r.x, j, f);
    sub_mod(r.x, r.x, v, f);
    sub_mod(r.x, r.x, v, f);             // X3 = r^2 - J - 2V
    sub_mod(t, v, r.x, f);
    mont_mul(t, t, rr, f);
    Fp s1j;
    mont_mul(s1j, s1, j, f);
    add_mod(s1j, s1j, s1j, f);
    sub_mod(r.y, t, s1j, f);             // Y3 = r(V - X3) - 2 S1 J
    add_mod(t, p.z, q.z, f);
    mont_sqr(t, t, f);
    sub_mod(t, t, z1z1, f);
    sub_mod(t, t, z2z2, f);
    mont_mul(r.z, t, h, f);              // Z3 = ((Z1+Z2)^2 - Z1Z1 - Z2Z2) H
}

// --- Pippenger MSM (signed digits + batch-affine accumulation) -------------

struct AffPt {
    Fp x, y;  // Montgomery form; MSM tracks infinity out-of-band
};

static inline void neg_mod(Fp &out, const Fp &a, const FieldCtx &f) {
    if (is_zero_fp(a)) { out = a; return; }
    sub_nored(out, f.mod, a);
}

// mixed addition r = p(Jac) + q(affine, finite): madd-2007-bl, 7M + 4S
static void jac_add_mixed(JacPoint &r, const JacPoint &p_in, const AffPt &q,
                          const FieldCtx &f) {
    JacPoint p = p_in;
    if (is_zero_fp(p.z)) { r.x = q.x; r.y = q.y; r.z = f.one; return; }
    Fp z1z1, u2, s2, h, hh, i, j, rr, v, t;
    mont_sqr(z1z1, p.z, f);
    mont_mul(u2, q.x, z1z1, f);
    mont_mul(s2, q.y, p.z, f);
    mont_mul(s2, s2, z1z1, f);
    sub_mod(h, u2, p.x, f);
    sub_mod(rr, s2, p.y, f);
    if (is_zero_fp(h)) {
        if (is_zero_fp(rr)) {
            JacPoint qj;
            qj.x = q.x; qj.y = q.y; qj.z = f.one;
            jac_double(r, qj, f);
            return;
        }
        r.z = Fp{{0, 0, 0, 0}};
        return;
    }
    mont_sqr(hh, h, f);                  // HH = H^2
    add_mod(i, hh, hh, f);
    add_mod(i, i, i, f);                 // I = 4*HH
    mont_mul(j, h, i, f);                // J = H*I
    add_mod(rr, rr, rr, f);              // r = 2*(S2-Y1)
    mont_mul(v, p.x, i, f);              // V = X1*I
    mont_sqr(r.x, rr, f);
    sub_mod(r.x, r.x, j, f);
    sub_mod(r.x, r.x, v, f);
    sub_mod(r.x, r.x, v, f);             // X3 = r^2 - J - 2V
    sub_mod(t, v, r.x, f);
    mont_mul(t, t, rr, f);
    Fp y1j;
    mont_mul(y1j, p.y, j, f);
    add_mod(y1j, y1j, y1j, f);
    sub_mod(r.y, t, y1j, f);             // Y3 = r*(V-X3) - 2*Y1*J
    add_mod(t, p.z, h, f);
    mont_sqr(t, t, f);
    sub_mod(t, t, z1z1, f);
    sub_mod(r.z, t, hh, f);              // Z3 = (Z1+H)^2 - Z1Z1 - HH
}

// r += k·p for a small positive k (the sparse bucket-reduction skip)
static void jac_add_small_mul(JacPoint &r, const JacPoint &p, u64 k,
                              const FieldCtx &f) {
    if (!k || is_zero_fp(p.z)) return;
    JacPoint acc;
    acc.z = Fp{{0, 0, 0, 0}};
    int top = 63 - __builtin_clzll(k);
    for (int bit = top; bit >= 0; --bit) {
        jac_double(acc, acc, f);
        if ((k >> bit) & 1) jac_add(acc, acc, p, f);
    }
    jac_add(r, r, acc, f);
}

// ===== AVX-512 IFMA 8-lane batched field engine =========================
//
// The batch-affine bucket accumulation below is mul-bound: ~6 Montgomery
// muls per pair-add, half of them on serial prefix/unwind chains. With
// vpmadd52 (8x52-bit lanes) the muls vectorize 8-wide IF the serial
// chains are split into 8 interleaved per-lane chains whose lane totals
// share one inversion — which is how level_pass_ifma() below is
// structured. Guarded at compile time (-march=native on an IFMA machine)
// and at runtime; every machine without it keeps the scalar path.

#if defined(__AVX512IFMA__) && defined(__AVX512F__)
#define PN_IFMA 1
#include <immintrin.h>

static const u64 MASK52 = (1ULL << 52) - 1;

struct Fp8 {  // 8 field elements, 5x52-bit limbs, lane-parallel
    __m512i l[5];
};

// The 5x52-limb CIOS below reduces by 2^260 per multiply, so the vector
// subsystem lives in the R' = 2^260 Montgomery domain while the scalar
// engine uses R = 2^256. Boundary conversions are one scalar mont_mul:
// x_w = mont_mul(x_s, c_in) (c_in = 2^260 mod p → X·2^260) and
// x_s = mont_mul(x_w, c_out) (c_out = 2^252 mod p → X·2^256).
struct Ctx52 {
    __m512i p[5];
    __m512i n0;     // -mod^{-1} mod 2^52, broadcast
    u64 p52[5];
    Fp c_in;        // 2^260 mod p (plain bits)
    Fp c_out;       // 2^252 mod p (plain bits)
};

static inline void fp_to52(const Fp &a, u64 out[5]) {
    out[0] = a.v[0] & MASK52;
    out[1] = ((a.v[0] >> 52) | (a.v[1] << 12)) & MASK52;
    out[2] = ((a.v[1] >> 40) | (a.v[2] << 24)) & MASK52;
    out[3] = ((a.v[2] >> 28) | (a.v[3] << 36)) & MASK52;
    out[4] = a.v[3] >> 16;
}

static inline void fp_from52(const u64 in[5], Fp &a) {
    a.v[0] = in[0] | (in[1] << 52);
    a.v[1] = (in[1] >> 12) | (in[2] << 40);
    a.v[2] = (in[2] >> 24) | (in[3] << 28);
    a.v[3] = (in[3] >> 36) | (in[4] << 16);
}

static Ctx52 make_ctx52(const FieldCtx &f) {
    Ctx52 c;
    fp_to52(f.mod, c.p52);
    for (int i = 0; i < 5; ++i) c.p[i] = _mm512_set1_epi64((long long)c.p52[i]);
    // the 2-adic inverse mod 2^64 truncates to the inverse mod 2^52
    c.n0 = _mm512_set1_epi64((long long)(f.inv & MASK52));
    // f.one = 2^256 mod p: shift by ±4 doublings/halvings mod p
    c.c_in = f.one;
    for (int i = 0; i < 4; ++i) add_mod(c.c_in, c.c_in, c.c_in, f);
    c.c_out = f.one;
    for (int i = 0; i < 4; ++i) {
        Fp t = c.c_out;
        if (t.v[0] & 1) {  // odd: add p, then halve
            u128 carry = 0;
            for (int j = 0; j < 4; ++j) {
                u128 s = (u128)t.v[j] + f.mod.v[j] + (u64)carry;
                t.v[j] = (u64)s;
                carry = s >> 64;
            }
            for (int j = 0; j < 3; ++j)
                t.v[j] = (t.v[j] >> 1) | (t.v[j + 1] << 63);
            t.v[3] = (t.v[3] >> 1) | ((u64)carry << 63);
        } else {
            for (int j = 0; j < 3; ++j)
                t.v[j] = (t.v[j] >> 1) | (t.v[j + 1] << 63);
            t.v[3] >>= 1;
        }
        c.c_out = t;
    }
    return c;
}

// boundary moves between the scalar (R = 2^256) and vector (R' = 2^260)
// Montgomery domains
static inline void to_w52(u64 out[5], const Fp &s, const Ctx52 &c,
                          const FieldCtx &f) {
    Fp w;
    mont_mul(w, s, c.c_in, f);
    fp_to52(w, out);
}

static inline void from_w52(Fp &out, const u64 in[5], const Ctx52 &c,
                            const FieldCtx &f) {
    Fp w;
    fp_from52(in, w);
    mont_mul(out, w, c.c_out, f);
}

static inline void v_load_lanes(Fp8 &dst, const u64 lanes[5][8]) {
    for (int i = 0; i < 5; ++i)
        dst.l[i] = _mm512_loadu_si512((const void *)lanes[i]);
}

// 8-wide CIOS Montgomery multiply; canonical (< p) in, canonical out.
static inline void v_mont_mul(Fp8 &out, const Fp8 &a, const Fp8 &b,
                              const Ctx52 &c) {
    __m512i acc[10];
    const __m512i zero = _mm512_setzero_si512();
    for (int i = 0; i < 10; ++i) acc[i] = zero;
    for (int i = 0; i < 5; ++i)
        for (int j = 0; j < 5; ++j) {
            acc[i + j] = _mm512_madd52lo_epu64(acc[i + j], a.l[i], b.l[j]);
            acc[i + j + 1] =
                _mm512_madd52hi_epu64(acc[i + j + 1], a.l[i], b.l[j]);
        }
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    for (int i = 0; i < 5; ++i) {
        acc[i + 1] = _mm512_add_epi64(acc[i + 1], _mm512_srli_epi64(acc[i], 52));
        __m512i lo = _mm512_and_si512(acc[i], mask);
        __m512i m = _mm512_madd52lo_epu64(zero, lo, c.n0);
        acc[i] = lo;
        for (int j = 0; j < 5; ++j) {
            acc[i + j] = _mm512_madd52lo_epu64(acc[i + j], m, c.p[j]);
            acc[i + j + 1] =
                _mm512_madd52hi_epu64(acc[i + j + 1], m, c.p[j]);
        }
        // acc[i] ≡ 0 mod 2^52 now; push its (1-bit) carry up
        acc[i + 1] = _mm512_add_epi64(acc[i + 1], _mm512_srli_epi64(acc[i], 52));
    }
    __m512i r[5];
    __m512i carry = zero;
    for (int i = 0; i < 5; ++i) {
        __m512i t = _mm512_add_epi64(acc[5 + i], carry);
        r[i] = _mm512_and_si512(t, mask);
        carry = _mm512_srli_epi64(t, 52);
    }
    // (< 2p; bits fit 5 limbs, so `carry` here is zero) — one
    // conditional subtract lands canonical
    __m512i borrow = zero;
    __m512i d[5];
    for (int i = 0; i < 5; ++i) {
        __m512i t = _mm512_sub_epi64(_mm512_sub_epi64(r[i], c.p[i]), borrow);
        d[i] = _mm512_and_si512(t, mask);
        borrow = _mm512_srli_epi64(t, 63);
    }
    __mmask8 ge = _mm512_cmpeq_epi64_mask(borrow, zero);  // r >= p lanes
    for (int i = 0; i < 5; ++i)
        out.l[i] = _mm512_mask_blend_epi64(ge, r[i], d[i]);
}

// 8-wide modular subtract, canonical in/out.
static inline void v_sub_mod(Fp8 &out, const Fp8 &a, const Fp8 &b,
                             const Ctx52 &c) {
    const __m512i zero = _mm512_setzero_si512();
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    __m512i borrow = zero;
    __m512i d[5];
    for (int i = 0; i < 5; ++i) {
        __m512i t = _mm512_sub_epi64(_mm512_sub_epi64(a.l[i], b.l[i]), borrow);
        d[i] = _mm512_and_si512(t, mask);
        borrow = _mm512_srli_epi64(t, 63);
    }
    __mmask8 neg = _mm512_cmpneq_epi64_mask(borrow, zero);  // a < b lanes
    __m512i carry = zero;
    for (int i = 0; i < 5; ++i) {
        __m512i e = _mm512_add_epi64(_mm512_add_epi64(d[i], c.p[i]), carry);
        carry = _mm512_srli_epi64(e, 52);
        out.l[i] = _mm512_mask_blend_epi64(neg, d[i], _mm512_and_si512(e, mask));
    }
}

static inline bool v_mul_selftest(const FieldCtx &f) {
    // 8 lanes of r2·r2 through the w-domain must round-trip to the
    // scalar product — a boot check of the 52-bit path + conversions
    Ctx52 c = make_ctx52(f);
    u64 lanes[5][8];
    u64 t[5];
    to_w52(t, f.r2, c, f);
    for (int i = 0; i < 5; ++i)
        for (int l = 0; l < 8; ++l) lanes[i][l] = t[i];
    Fp8 a;
    v_load_lanes(a, lanes);
    Fp8 o;
    v_mont_mul(o, a, a, c);
    u64 got[5][8];
    for (int i = 0; i < 5; ++i)
        _mm512_storeu_si512((void *)got[i], o.l[i]);
    Fp expect;
    mont_mul(expect, f.r2, f.r2, f);
    for (int l = 0; l < 8; ++l) {
        u64 g[5] = {got[0][l], got[1][l], got[2][l], got[3][l], got[4][l]};
        Fp back;
        from_w52(back, g, c, f);
        for (int i = 0; i < 4; ++i)
            if (back.v[i] != expect.v[i]) return false;
    }
    return true;
}

static bool ifma_available() {
    static int cached = -1;
    if (cached < 0) {
        __builtin_cpu_init();
        cached = __builtin_cpu_supports("avx512ifma") ? 1 : 0;
    }
    return cached == 1;
}

// gather 8 elements of a 5x52-limb AoS array (40 B stride) by index
static inline void vgather5(Fp8 &dst, const u64 *base, const __m512i idx5) {
    for (int i = 0; i < 5; ++i)
        dst.l[i] = _mm512_i64gather_epi64(
            _mm512_add_epi64(idx5, _mm512_set1_epi64(i)), base, 8);
}

// reusable per-MSM scratch: a fresh allocation per level call costs
// ~170 MB of page faults at 2^20 and swamps the vector math
struct IfmaScratch {
    std::vector<Fp8> prefv, denv, axv, ayv, bxv, byv;
    std::vector<u64> pox, poy;
    std::vector<unsigned char> kind;
    std::vector<long> heads;
    void ensure(long pairs) {
        const long nblk = (pairs + 7) / 8;
        if ((long)prefv.size() < nblk) {
            prefv.resize(nblk);
            denv.resize(nblk);
            axv.resize(nblk);
            ayv.resize(nblk);
            bxv.resize(nblk);
            byv.resize(nblk);
        }
        if ((long)pox.size() < 5 * pairs) {
            pox.resize(5 * (size_t)pairs);
            poy.resize(5 * (size_t)pairs);
        }
        if ((long)kind.size() < pairs) kind.resize(pairs);
        if ((long)heads.size() < pairs) heads.resize(pairs);
    }
};

// One batch-affine level, 8-wide over a 52-bit AoS working set:
// per-lane den chains built forward, lane totals batch-inverted once,
// chains unwound backward, the affine adds evaluated in vector lanes.
// Mirrors the scalar level exactly — same pairing, same edge rules
// (doubling / cancel-to-infinity), same output order. ax52/ay52 hold
// 5x52-bit limbs per element (canonical Montgomery values); abid the
// bucket ids. Pure in→out (callers swap their ping-pong buffers);
// returns the new live count.
static long level_pass_ifma(const FieldCtx &f, const Ctx52 &c52,
                            const u64 *ax52, const u64 *ay52,
                            const int32_t *abid,
                            u64 *nx52, u64 *ny52, int32_t *nbid,
                            const std::vector<unsigned char> &role,
                            long m, long pairs, IfmaScratch &S) {
    S.ensure(pairs);
    std::vector<long> &heads = S.heads;
    {
        long pi = 0;
        for (long i = 0; i < m; ++i)
            if (role[i] == 1) heads[pi++] = i;
    }
    const long nblk = (pairs + 7) / 8;
    // per-block saved state so pass 2 re-reads nothing from the source
    std::vector<Fp8> &prefv = S.prefv, &denv = S.denv, &axv = S.axv,
                     &ayv = S.ayv, &bxv = S.bxv, &byv = S.byv;
    std::vector<unsigned char> &kind = S.kind;
    std::memset(kind.data(), 0, pairs);
    // w-domain multiplicative identity: v_mont_mul(x, e) = x needs
    // e = 2^260 mod p — c_in's bit pattern, not f.one's
    u64 one52[5];
    fp_to52(c52.c_in, one52);

    // DUAL prefix chains (r4): even/odd blocks run two independent
    // den-product chains that merge only at the single batch
    // inversion, keeping two v_mont_muls in flight. Measured ~neutral
    // on this box (the level pass is vgather-bound, not chain-latency
    // bound) but strictly never worse; retained with the scalar-vs-
    // vector equivalence test pinning correctness.
    Fp8 run[2];
    for (int ch = 0; ch < 2; ++ch)
        for (int i = 0; i < 5; ++i)
            run[ch].l[i] = _mm512_set1_epi64((long long)one52[i]);
    const __m512i vzero = _mm512_setzero_si512();

    // pass 1: gather head/tail coords, den = xB − xA, per-lane chains
    for (long b = 0; b < nblk; ++b) {
        int cnt = (int)((b == nblk - 1) ? pairs - 8 * b : 8);
        alignas(64) long long hoff[8];
        for (int l = 0; l < 8; ++l) {
            long h = (l < cnt) ? heads[8 * b + l] : heads[8 * b];  // dup pad
            hoff[l] = 5 * h;
        }
        const __m512i hv = _mm512_load_si512((const void *)hoff);
        const __m512i tv = _mm512_add_epi64(hv, _mm512_set1_epi64(5));
        Fp8 Ax, Ay, Bx, By, den;
        vgather5(Ax, ax52, hv);
        vgather5(Ay, ay52, hv);
        vgather5(Bx, ax52, tv);
        vgather5(By, ay52, tv);
        v_sub_mod(den, Bx, Ax, c52);
        __m512i nz = den.l[0];
        for (int i = 1; i < 5; ++i) nz = _mm512_or_si512(nz, den.l[i]);
        __mmask8 zl = _mm512_cmpeq_epi64_mask(nz, vzero);
        if (cnt < 8) zl = (__mmask8)(zl | (0xFF << cnt));  // pad lanes
        if (zl) {
            u64 dl[5][8], ayl[5][8], byl[5][8];
            for (int i = 0; i < 5; ++i) {
                _mm512_storeu_si512((void *)dl[i], den.l[i]);
                _mm512_storeu_si512((void *)ayl[i], Ay.l[i]);
                _mm512_storeu_si512((void *)byl[i], By.l[i]);
            }
            for (int l = 0; l < 8; ++l) {
                if (!((zl >> l) & 1)) continue;
                u64 t[5];
                if (l >= cnt) {
                    std::memcpy(t, one52, 40);  // pad: den=1, no output
                } else {
                    Fp aY, bY, sy;
                    u64 a5[5] = {ayl[0][l], ayl[1][l], ayl[2][l], ayl[3][l],
                                 ayl[4][l]};
                    u64 b5[5] = {byl[0][l], byl[1][l], byl[2][l], byl[3][l],
                                 byl[4][l]};
                    fp_from52(a5, aY);
                    fp_from52(b5, bY);
                    add_mod(sy, aY, bY, f);
                    if (is_zero_fp(sy)) {
                        kind[8 * b + l] = 2;  // P + (−P): drops out
                        std::memcpy(t, one52, 40);
                    } else {
                        kind[8 * b + l] = 1;  // doubling: den = 2y
                        Fp dd;
                        add_mod(dd, aY, aY, f);
                        fp_to52(dd, t);
                    }
                }
                for (int i = 0; i < 5; ++i) dl[i][l] = t[i];
            }
            v_load_lanes(den, dl);
        }
        const int ch = (int)(b & 1);
        prefv[b] = run[ch];
        denv[b] = den;
        axv[b] = Ax;
        ayv[b] = Ay;
        bxv[b] = Bx;
        byv[b] = By;
        v_mont_mul(run[ch], run[ch], den, c52);
    }

    // lane totals (both chains) -> ONE inversion -> per-chain seeds
    Fp8 inv_vec[2];
    {
        Fp lane_tot[16], pre[16], inv_lane[16];
        u64 lanes[2][5][8];
        for (int ch = 0; ch < 2; ++ch)
            for (int i = 0; i < 5; ++i)
                _mm512_storeu_si512((void *)lanes[ch][i], run[ch].l[i]);
        for (int j = 0; j < 16; ++j) {
            int ch = j >> 3, l = j & 7;
            u64 t[5] = {lanes[ch][0][l], lanes[ch][1][l], lanes[ch][2][l],
                        lanes[ch][3][l], lanes[ch][4][l]};
            from_w52(lane_tot[j], t, c52, f);  // w → s domain
        }
        Fp acc = f.one;
        for (int j = 0; j < 16; ++j) {
            pre[j] = acc;
            mont_mul(acc, acc, lane_tot[j], f);
        }
        Fp tinv;
        mont_inv(tinv, acc, f);
        for (int j = 15; j >= 0; --j) {
            mont_mul(inv_lane[j], tinv, pre[j], f);
            mont_mul(tinv, tinv, lane_tot[j], f);
        }
        u64 t[5];
        for (int j = 0; j < 16; ++j) {
            int ch = j >> 3, l = j & 7;
            to_w52(t, inv_lane[j], c52, f);  // s → w domain
            for (int i = 0; i < 5; ++i) lanes[ch][i][l] = t[i];
        }
        for (int ch = 0; ch < 2; ++ch)
            v_load_lanes(inv_vec[ch], lanes[ch]);
    }

    // pass 2 (backward): unwind chains, evaluate the adds into a dense
    // 52-bit pair-output array
    std::vector<u64> &pox = S.pox, &poy = S.poy;
    for (long b = nblk - 1; b >= 0; --b) {
        int cnt = (int)((b == nblk - 1) ? pairs - 8 * b : 8);
        const int ch = (int)(b & 1);
        Fp8 dinv, num;
        v_mont_mul(dinv, inv_vec[ch], prefv[b], c52);
        v_mont_mul(inv_vec[ch], inv_vec[ch], denv[b], c52);
        const Fp8 &Ax = axv[b], &Ay = ayv[b], &Bx = bxv[b], &By = byv[b];
        v_sub_mod(num, By, Ay, c52);
        bool patch = false;
        for (int l = 0; l < cnt; ++l)
            if (kind[8 * b + l] == 1) patch = true;
        if (patch) {
            u64 lanes[5][8], axl[5][8];
            for (int i = 0; i < 5; ++i) {
                _mm512_storeu_si512((void *)lanes[i], num.l[i]);
                _mm512_storeu_si512((void *)axl[i], Ax.l[i]);
            }
            for (int l = 0; l < cnt; ++l) {
                if (kind[8 * b + l] != 1) continue;
                u64 a5[5] = {axl[0][l], axl[1][l], axl[2][l], axl[3][l],
                             axl[4][l]};
                Fp aX, sq, n3;
                fp_from52(a5, aX);       // raw w-form bits X·2^260
                mont_sqr(sq, aX, f);     // X²·2^264
                mont_mul(sq, sq, c52.c_out, f);  // X²·2^260 — back in w
                add_mod(n3, sq, sq, f);
                add_mod(n3, n3, sq, f);  // 3x²
                u64 t[5];
                fp_to52(n3, t);
                for (int i = 0; i < 5; ++i) lanes[i][l] = t[i];
            }
            v_load_lanes(num, lanes);
        }
        Fp8 lam, x3, y3, t0;
        v_mont_mul(lam, num, dinv, c52);
        v_mont_mul(x3, lam, lam, c52);
        v_sub_mod(x3, x3, Ax, c52);
        v_sub_mod(x3, x3, Bx, c52);
        v_sub_mod(t0, Ax, x3, c52);
        v_mont_mul(y3, lam, t0, c52);
        v_sub_mod(y3, y3, Ay, c52);
        // dense stride-5 scatter of the block's outputs
        alignas(64) long long ooff[8];
        for (int l = 0; l < 8; ++l)
            ooff[l] = 5 * (8 * b + ((l < cnt) ? l : cnt - 1));
        const __m512i ov = _mm512_load_si512((const void *)ooff);
        __mmask8 live = (__mmask8)((1u << cnt) - 1);
        for (int i = 0; i < 5; ++i) {
            _mm512_mask_i64scatter_epi64(
                pox.data(), live,
                _mm512_add_epi64(ov, _mm512_set1_epi64(i)), x3.l[i], 8);
            _mm512_mask_i64scatter_epi64(
                poy.data(), live,
                _mm512_add_epi64(ov, _mm512_set1_epi64(i)), y3.l[i], 8);
        }
    }

    // merge (forward, order-preserving — matches the scalar backward fill)
    long write = 0, pi = 0;
    for (long i = 0; i < m; ++i) {
        if (role[i] == 2) continue;
        if (role[i] == 1) {
            if (kind[pi] != 2) {
                std::memcpy(&nx52[5 * write], &pox[5 * pi], 40);
                std::memcpy(&ny52[5 * write], &poy[5 * pi], 40);
                nbid[write] = abid[i];
                ++write;
            }
            ++pi;
        } else {
            std::memcpy(&nx52[5 * write], &ax52[5 * i], 40);
            std::memcpy(&ny52[5 * write], &ay52[5 * i], 40);
            nbid[write] = abid[i];
            ++write;
        }
    }
    return write;
}
#endif  // PN_IFMA

// Pippenger MSM: bases affine standard-form (x,y) pairs (8 limbs each,
// zero-zero = identity), scalars standard-form 4-limb. Result affine
// standard form written to out (8 limbs; zeros for identity).
//
// Signed-digit windows (buckets halved) with batch-affine bucket
// accumulation: per window, points are counting-sorted by |digit| and
// each bucket's segment is summed level-by-level as independent affine
// additions sharing ONE batched inversion per level (~6M per add vs 16M
// for Jacobian-Jacobian). Windows with no nonzero digit are skipped
// outright, which makes small-scalar MSMs (0/1 selector columns) cost a
// single window pass.
void g1_msm(const u64 *mod_limbs, const u64 *bases, const u64 *scalars,
            long n, u64 *out) {
    FieldCtx f = make_ctx(mod_limbs);
#ifdef PN_IFMA
    const bool use_ifma = !std::getenv("PN_NO_IFMA") && ifma_available() &&
                          v_mul_selftest(f);
    Ctx52 c52;
    if (use_ifma) c52 = make_ctx52(f);
#endif
    int c = 4;
    if (n > 32) c = 8;
    if (n > 1024) c = 12;
    if (n > 131072) c = 15;  // r4 grid on the IFMA box: c=15 beats 16
                             // by ~8% at 2^20 (PN_MSM_C overrides)
    if (const char *cenv = std::getenv("PN_MSM_C")) {
        int cv = std::atoi(cenv);
        if (cv >= 2 && cv <= 20) c = cv;
    }
    const long half = 1L << (c - 1);
    const int windows = (256 + c - 1) / c + 1;  // +1 for the signed carry

    std::vector<AffPt> pts(n);
    std::vector<unsigned char> finite(n);
    long n_finite = 0;
    for (long i = 0; i < n; ++i) {
        Fp x, y;
        std::memcpy(x.v, bases + 8 * i, 32);
        std::memcpy(y.v, bases + 8 * i + 4, 32);
        bool inf = is_zero_fp(x) && is_zero_fp(y);
        finite[i] = !inf;
        if (!inf) {
            to_mont(pts[i].x, x, f);
            to_mont(pts[i].y, y, f);
            ++n_finite;
        }
    }

    // signed-digit recode: scalar = Σ d_w·2^{cw}, d_w ∈ [-2^{c-1}, 2^{c-1}]
    std::vector<int32_t> digits((size_t)windows * n, 0);
    for (long i = 0; i < n; ++i) {
        if (!finite[i]) continue;
        u64 carry = 0;
        for (int w = 0; w < windows; ++w) {
            long bit0 = (long)w * c;
            u64 raw = 0;
            if (bit0 < 256) {
                int word = (int)(bit0 / 64), off = (int)(bit0 % 64);
                raw = scalars[4 * i + word] >> off;
                if (off && word + 1 < 4)
                    raw |= scalars[4 * i + word + 1] << (64 - off);
                raw &= ((u64)1 << c) - 1;
            }
            raw += carry;
            if (raw > (u64)half) {
                digits[(size_t)w * n + i] = (int32_t)raw - (int32_t)(1L << c);
                carry = 1;
            } else {
                digits[(size_t)w * n + i] = (int32_t)raw;
                carry = 0;
            }
        }
    }

    // per-level scratch (ping-pong): x, y, bucket id
    std::vector<Fp> ax(n_finite), ay(n_finite), nx(n_finite), ny(n_finite);
    std::vector<int32_t> abid(n_finite), nbid(n_finite);
    std::vector<long> counts(half + 1);
    std::vector<Fp> dens, prefix;
    dens.reserve(n_finite / 2 + 1);
    prefix.reserve(n_finite / 2 + 1);
#ifdef PN_IFMA
    // 52-bit AoS twins for the vectorized levels (built lazily per
    // window; the tail levels fall back to the scalar path)
    std::vector<u64> x52, y52, nx52, ny52, p52x, p52y, p52yn;
    IfmaScratch ifma_scratch;
    if (use_ifma) {
        x52.resize(5 * (size_t)n_finite);
        y52.resize(5 * (size_t)n_finite);
        nx52.resize(5 * (size_t)n_finite);
        ny52.resize(5 * (size_t)n_finite);
        // per-point w-domain coordinates (and negated y for the signed
        // digits), converted once — window placement is then a memcpy
        p52x.resize(5 * (size_t)n);
        p52y.resize(5 * (size_t)n);
        p52yn.resize(5 * (size_t)n);
        for (long i = 0; i < n; ++i) {
            if (!finite[i]) continue;
            to_w52(&p52x[5 * (size_t)i], pts[i].x, c52, f);
            to_w52(&p52y[5 * (size_t)i], pts[i].y, c52, f);
            Fp yn;
            neg_mod(yn, pts[i].y, f);
            to_w52(&p52yn[5 * (size_t)i], yn, c52, f);
        }
    }
#endif

    // PN_MSM_DEBUG=1: phase timing to stderr (sort/levels/reduction)
    const bool dbg = std::getenv("PN_MSM_DEBUG") != nullptr;
    double t_sort = 0, t_levels = 0, t_reduce = 0, t_dbl = 0;
    auto now = [] { return std::chrono::steady_clock::now(); };
    auto secs = [](auto a, auto b) {
        return std::chrono::duration<double>(b - a).count();
    };

    JacPoint total;
    total.z = Fp{{0, 0, 0, 0}};
    for (int w = windows - 1; w >= 0; --w) {
        auto tw0 = now();
        if (!is_zero_fp(total.z))
            for (int d = 0; d < c; ++d) jac_double(total, total, f);
        t_dbl += secs(tw0, now());
        auto ts0 = now();
        const int32_t *dw = &digits[(size_t)w * n];

        // counting sort by |digit|, sign applied to y on placement
        std::fill(counts.begin(), counts.end(), 0);
        long m = 0;
        for (long i = 0; i < n; ++i)
            if (dw[i]) { ++counts[dw[i] < 0 ? -dw[i] : dw[i]]; ++m; }
        if (!m) continue;
        long acc_off = 0;
        for (long b = 1; b <= half; ++b) {
            long cnt = counts[b];
            counts[b] = acc_off;
            acc_off += cnt;
        }
        for (long i = 0; i < n; ++i) {
            int32_t d = dw[i];
            if (!d) continue;
            long b = d < 0 ? -d : d;
            long pos = counts[b]++;
#ifdef PN_IFMA
            if (use_ifma) {
                std::memcpy(&x52[5 * (size_t)pos], &p52x[5 * (size_t)i], 40);
                std::memcpy(&y52[5 * (size_t)pos],
                            d > 0 ? &p52y[5 * (size_t)i]
                                  : &p52yn[5 * (size_t)i], 40);
                abid[pos] = (int32_t)b;
                continue;
            }
#endif
            ax[pos] = pts[i].x;
            if (d > 0) ay[pos] = pts[i].y;
            else neg_mod(ay[pos], pts[i].y, f);
            abid[pos] = (int32_t)b;
        }

        t_sort += secs(ts0, now());
        auto tl0 = now();
        // level-by-level batch-affine segment sums. Each level pairs
        // adjacent same-bucket entries; all pair additions share one
        // batched inversion (Montgomery trick).
        std::vector<unsigned char> role(n_finite);  // 0=solo 1=pair-first
#ifdef PN_IFMA
        bool in52 = use_ifma;  // placement wrote the w-domain arrays
#else
        bool in52 = false;
#endif
        (void)in52;
        while (true) {
            // fix the pairing once (greedy adjacent within segments) so
            // both passes below agree for odd-length segments
            long pairs = 0;
            for (long i = 0; i < m;) {
                if (i + 1 < m && abid[i + 1] == abid[i]) {
                    role[i] = 1;
                    role[i + 1] = 2;
                    ++pairs;
                    i += 2;
                } else {
                    role[i] = 0;
                    ++i;
                }
            }
            if (!pairs) break;
#ifdef PN_IFMA
            if (use_ifma && in52 && pairs >= 64) {
                m = level_pass_ifma(f, c52, x52.data(), y52.data(),
                                    abid.data(), nx52.data(), ny52.data(),
                                    nbid.data(), role, m, pairs,
                                    ifma_scratch);
                x52.swap(nx52);
                y52.swap(ny52);
                abid.swap(nbid);
                continue;
            }
            if (in52) {  // tail levels: back to the scalar (s) domain
                for (long i = 0; i < m; ++i) {
                    from_w52(ax[i], &x52[5 * (size_t)i], c52, f);
                    from_w52(ay[i], &y52[5 * (size_t)i], c52, f);
                }
                in52 = false;
            }
#endif
            dens.clear();
            prefix.clear();
            // pass 1: denominators + running product
            Fp run = f.one;
            std::vector<unsigned char> kind; // 0=add 1=double 2=infinity
            kind.reserve(pairs);
            for (long i = 0; i < m; ++i) {
                if (role[i] != 1) continue;
                Fp d;
                sub_mod(d, ax[i + 1], ax[i], f);
                if (is_zero_fp(d)) {
                    Fp sy;
                    add_mod(sy, ay[i], ay[i + 1], f);
                    if (is_zero_fp(sy)) { kind.push_back(2); d = f.one; }
                    else { kind.push_back(1); add_mod(d, ay[i], ay[i], f); }
                } else kind.push_back(0);
                dens.push_back(d);
                prefix.push_back(run);
                mont_mul(run, run, d, f);
            }
            Fp inv;
            mont_inv(inv, run, f);
            // count outputs: infinity pairs drop out
            long n_out = m - pairs;
            for (long pi = 0; pi < pairs; ++pi)
                if (kind[pi] == 2) --n_out;
            // pass 2 (backward): per-pair inverse, then the affine add
            long write = n_out;
            long pi = pairs - 1;
            for (long i = m - 1; i >= 0; --i) {
                if (role[i] == 2) continue;  // handled with its pair head
                if (role[i] == 1) {
                    Fp dinv;
                    mont_mul(dinv, inv, prefix[pi], f);
                    mont_mul(inv, inv, dens[pi], f);
                    if (kind[pi] != 2) {
                        long a = i, b = i + 1;
                        Fp lam, num, x3, y3;
                        if (kind[pi] == 1) {
                            mont_sqr(num, ax[a], f);
                            Fp n3;
                            add_mod(n3, num, num, f);
                            add_mod(num, n3, num, f);  // 3x^2
                        } else {
                            sub_mod(num, ay[b], ay[a], f);
                        }
                        mont_mul(lam, num, dinv, f);
                        mont_sqr(x3, lam, f);
                        sub_mod(x3, x3, ax[a], f);
                        sub_mod(x3, x3, ax[b], f);
                        sub_mod(y3, ax[a], x3, f);
                        mont_mul(y3, y3, lam, f);
                        sub_mod(y3, y3, ay[a], f);
                        --write;
                        nx[write] = x3;
                        ny[write] = y3;
                        nbid[write] = abid[i];
                    }
                    --pi;
                } else {
                    --write;
                    nx[write] = ax[i];
                    ny[write] = ay[i];
                    nbid[write] = abid[i];
                }
            }
            m = n_out;
            ax.swap(nx);
            ay.swap(ny);
            abid.swap(nbid);
        }

#ifdef PN_IFMA
        if (in52) {  // vector levels ran last: rebuild the Fp survivors
            for (long i = 0; i < m; ++i) {
                from_w52(ax[i], &x52[5 * (size_t)i], c52, f);
                from_w52(ay[i], &y52[5 * (size_t)i], c52, f);
            }
        }
#endif
        t_levels += secs(tl0, now());
        auto tr0 = now();
        // bucket reduction: one affine point per surviving bucket id,
        // ascending. Walk descending with the running/sum scan; empty
        // gaps advance `sum` by gap·running via a small double-and-add.
        JacPoint running, sum;
        running.z = Fp{{0, 0, 0, 0}};
        sum.z = Fp{{0, 0, 0, 0}};
        long prev_b = half + 1;
        for (long i = m - 1; i >= 0; --i) {
            long b = abid[i];
            jac_add_small_mul(sum, running, (u64)(prev_b - b - 1), f);
            AffPt q;
            q.x = ax[i];
            q.y = ay[i];
            jac_add_mixed(running, running, q, f);
            jac_add(sum, sum, running, f);
            prev_b = b;
        }
        jac_add_small_mul(sum, running, (u64)(prev_b - 1), f);
        jac_add(total, total, sum, f);
        t_reduce += secs(tr0, now());
    }
    if (dbg) {
#ifdef PN_IFMA
        std::fprintf(stderr, "g1_msm ifma=%d\n", (int)use_ifma);
#endif
        std::fprintf(stderr,
                     "g1_msm n=%ld c=%d: dbl %.2fs sort %.2fs levels %.2fs "
                     "reduce %.2fs\n",
                     n, c, t_dbl, t_sort, t_levels, t_reduce);
    }

    // to affine
    if (is_zero_fp(total.z)) {
        std::memset(out, 0, 64);
        return;
    }
    Fp zinv, zinv2, zinv3, axx, ayy;
    mont_inv(zinv, total.z, f);
    mont_sqr(zinv2, zinv, f);
    mont_mul(zinv3, zinv2, zinv, f);
    mont_mul(axx, total.x, zinv2, f);
    mont_mul(ayy, total.y, zinv3, f);
    from_mont(axx, axx, f);
    from_mont(ayy, ayy, f);
    std::memcpy(out, axx.v, 32);
    std::memcpy(out + 4, ayy.v, 32);
}

// ===== multi-column MSM: K commit columns through one engine call =======
//
// The prover's commit wall is K independent g1_msm calls over the SAME
// base array (SRS / Lagrange powers): each call re-parses and
// re-converts every base point, re-recodes into a windows·n digit
// array, and walks cache-hostile monolithic level passes. g1_msm_multi
// restructures the whole path around what the r8 measurements actually
// showed:
//
//   - bases are parsed + Montgomery/w-domain-converted ONCE for all K
//     columns (serial: K times — ~0.35 s/column at 2^20);
//   - windows are processed LSB→MSB with on-the-fly signed recode
//     (carry byte per scalar), so no windows·n digit array is ever
//     materialized or re-streamed;
//   - the batch-affine pairing levels run per BUCKET-RANGE TILE: a
//     tile of TBUK buckets' entries stays L2-resident across ALL of
//     its levels (the monolithic pass streams the whole working set
//     once per level), with pair sums evaluated by affine_pairs_ifma
//     and compacted in place — no role scans, no merge copies, no
//     ping-pong arrays;
//   - the per-window bucket reduction runs 32 group-chains wide in
//     IFMA lanes (reduce_column_ifma) — the serial telescope is the
//     one part of Pippenger a single column cannot vectorize, and it
//     was ~25% of a 2^18 serial MSM;
//   - cross-column sharing INSIDE one window pass (i-outer/k-inner
//     placement feeding K bucket placements per base fetch, K× wider
//     inversion levels) is supported via PN_MSM_KB but measured net
//     NEGATIVE on the r8 box — the chunk·n working set costs more in
//     cache/TLB than the shared reads save — so the default sweeps
//     one column per window pass (see the KB comment in the driver).
//
// Each window's bucket total is shifted by c·w doublings before
// joining its column total. Per column the result is bit-exact with
// g1_msm (canonical affine output); g1_msm itself is left untouched as
// the committed-baseline oracle the BENCH_r08 curve is measured
// against. ``flips`` (optional, K×n bytes) negates a base's y for one
// column only — the scalar-balancing trick (_msm_signed) without K
// private copies of the base array.

#ifdef PN_IFMA

static inline void v_add_mod(Fp8 &out, const Fp8 &a, const Fp8 &b,
                             const Ctx52 &c) {
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    const __m512i zero = _mm512_setzero_si512();
    __m512i carry = zero;
    __m512i s[5];
    for (int i = 0; i < 5; ++i) {
        __m512i t = _mm512_add_epi64(_mm512_add_epi64(a.l[i], b.l[i]),
                                     carry);
        s[i] = _mm512_and_si512(t, mask);
        carry = _mm512_srli_epi64(t, 52);
    }
    // canonical operands: sum < 2p fits 5 limbs — one conditional
    // subtract lands canonical (same pattern as v_mont_mul's tail)
    __m512i borrow = zero;
    __m512i d[5];
    for (int i = 0; i < 5; ++i) {
        __m512i t = _mm512_sub_epi64(_mm512_sub_epi64(s[i], c.p[i]),
                                     borrow);
        d[i] = _mm512_and_si512(t, mask);
        borrow = _mm512_srli_epi64(t, 63);
    }
    __mmask8 ge = _mm512_cmpeq_epi64_mask(borrow, zero);
    for (int i = 0; i < 5; ++i)
        out.l[i] = _mm512_mask_blend_epi64(ge, s[i], d[i]);
}

struct Jac8 {  // 8 Jacobian points, lane-parallel, w-domain 5x52 limbs
    Fp8 x, y, z;
};

static inline __mmask8 v_is_zero5(const Fp8 &a) {
    __m512i nz = a.l[0];
    for (int i = 1; i < 5; ++i) nz = _mm512_or_si512(nz, a.l[i]);
    return _mm512_cmpeq_epi64_mask(nz, _mm512_setzero_si512());
}

static inline void v_blend5(Fp8 &dst, __mmask8 m, const Fp8 &src) {
    for (int i = 0; i < 5; ++i)
        dst.l[i] = _mm512_mask_blend_epi64(m, dst.l[i], src.l[i]);
}

static inline void lane_get5(const Fp8 &a, int l, u64 out[5]) {
    alignas(64) u64 tmp[8];
    for (int i = 0; i < 5; ++i) {
        _mm512_store_si512((void *)tmp, a.l[i]);
        out[i] = tmp[l];
    }
}

static inline void lane_set5(Fp8 &a, int l, const u64 in[5]) {
    alignas(64) u64 tmp[8];
    for (int i = 0; i < 5; ++i) {
        _mm512_store_si512((void *)tmp, a.l[i]);
        tmp[l] = in[i];
        a.l[i] = _mm512_load_si512((const void *)tmp);
    }
}

static void jac_from_lane(const Jac8 &v, int l, JacPoint &p,
                          const Ctx52 &c, const FieldCtx &f) {
    u64 t[5];
    lane_get5(v.x, l, t);
    from_w52(p.x, t, c, f);
    lane_get5(v.y, l, t);
    from_w52(p.y, t, c, f);
    lane_get5(v.z, l, t);
    from_w52(p.z, t, c, f);
}

static void jac_to_lane(Jac8 &v, int l, const JacPoint &p,
                        const Ctx52 &c, const FieldCtx &f) {
    u64 t[5];
    to_w52(t, p.x, c, f);
    lane_set5(v.x, l, t);
    to_w52(t, p.y, c, f);
    lane_set5(v.y, l, t);
    to_w52(t, p.z, c, f);
    lane_set5(v.z, l, t);
}

static inline void vgather5_mask(Fp8 &dst, const u64 *base,
                                 const __m512i idx5, __mmask8 mk) {
    for (int i = 0; i < 5; ++i)
        dst.l[i] = _mm512_mask_i64gather_epi64(
            _mm512_setzero_si512(), mk,
            _mm512_add_epi64(idx5, _mm512_set1_epi64(i)), base, 8);
}

// p[t] += q[t] (q affine, per-lane ``present`` masks) for 4 independent
// Jac8 states at once — madd-2007-bl, the vector twin of jac_add_mixed.
// Every primitive runs 4× back to back on independent chains, so the
// reduction loop is throughput-bound instead of serialized on one
// chain's v_mont_mul latency. Lanes where p is at infinity take q;
// equal-x lanes (doubling / cancel) resolve through the exact scalar
// path, so every input is handled exactly.
#define VJ4(expr) for (int t = 0; t < 4; ++t) { expr; }
static void v_jac_add_mixed4(Jac8 p[4], const Fp8 qx[4], const Fp8 qy[4],
                             const __mmask8 present[4], const Ctx52 &c52,
                             const FieldCtx &f, const Fp8 &onev) {
    __mmask8 pinf[4], gen[4], hz[4];
    Fp8 z1z1[4], u2[4], s2[4], h[4], rr[4], hh[4], i4[4], j[4], v[4],
        t0[4], x3[4], y3[4], z3[4], y1j[4], zh[4];
    VJ4(pinf[t] = (__mmask8)(v_is_zero5(p[t].z) & present[t]))
    VJ4(gen[t] = (__mmask8)(present[t] & ~pinf[t]))
    VJ4(v_mont_mul(z1z1[t], p[t].z, p[t].z, c52))
    VJ4(v_mont_mul(u2[t], qx[t], z1z1[t], c52))
    VJ4(v_mont_mul(s2[t], qy[t], p[t].z, c52))
    VJ4(v_mont_mul(s2[t], s2[t], z1z1[t], c52))
    VJ4(v_sub_mod(h[t], u2[t], p[t].x, c52))
    VJ4(v_sub_mod(rr[t], s2[t], p[t].y, c52))
    VJ4(hz[t] = (__mmask8)(v_is_zero5(h[t]) & gen[t]))
    VJ4(v_mont_mul(hh[t], h[t], h[t], c52))
    VJ4(v_add_mod(i4[t], hh[t], hh[t], c52))
    VJ4(v_add_mod(i4[t], i4[t], i4[t], c52))
    VJ4(v_mont_mul(j[t], h[t], i4[t], c52))
    VJ4(v_add_mod(rr[t], rr[t], rr[t], c52))
    VJ4(v_mont_mul(v[t], p[t].x, i4[t], c52))
    VJ4(v_mont_mul(x3[t], rr[t], rr[t], c52))
    VJ4(v_sub_mod(x3[t], x3[t], j[t], c52))
    VJ4(v_sub_mod(x3[t], x3[t], v[t], c52))
    VJ4(v_sub_mod(x3[t], x3[t], v[t], c52))
    VJ4(v_sub_mod(t0[t], v[t], x3[t], c52))
    VJ4(v_mont_mul(t0[t], t0[t], rr[t], c52))
    VJ4(v_mont_mul(y1j[t], p[t].y, j[t], c52))
    VJ4(v_add_mod(y1j[t], y1j[t], y1j[t], c52))
    VJ4(v_sub_mod(y3[t], t0[t], y1j[t], c52))
    VJ4(v_add_mod(zh[t], p[t].z, h[t], c52))
    VJ4(v_mont_mul(zh[t], zh[t], zh[t], c52))
    VJ4(v_sub_mod(zh[t], zh[t], z1z1[t], c52))
    VJ4(v_sub_mod(z3[t], zh[t], hh[t], c52))
    for (int t = 0; t < 4; ++t) {
        if (!hz[t]) continue;  // rare: exact scalar resolution per lane
        for (int l = 0; l < 8; ++l) {
            if (!((hz[t] >> l) & 1)) continue;
            JacPoint pl, res;
            jac_from_lane(p[t], l, pl, c52, f);
            AffPt q;
            u64 tt[5];
            lane_get5(qx[t], l, tt);
            from_w52(q.x, tt, c52, f);
            lane_get5(qy[t], l, tt);
            from_w52(q.y, tt, c52, f);
            jac_add_mixed(res, pl, q, f);
            Jac8 tmp;  // route through jac_to_lane on a scratch triple
            tmp.x = x3[t];
            tmp.y = y3[t];
            tmp.z = z3[t];
            jac_to_lane(tmp, l, res, c52, f);
            x3[t] = tmp.x;
            y3[t] = tmp.y;
            z3[t] = tmp.z;
        }
    }
    for (int t = 0; t < 4; ++t) {
        v_blend5(p[t].x, gen[t], x3[t]);
        v_blend5(p[t].y, gen[t], y3[t]);
        v_blend5(p[t].z, gen[t], z3[t]);
        v_blend5(p[t].x, pinf[t], qx[t]);
        v_blend5(p[t].y, pinf[t], qy[t]);
        v_blend5(p[t].z, pinf[t], onev);
    }
}

// p[t] += q[t] (both Jacobian) × 4 chains — add-2007-bl, the vector
// twin of jac_add, same 4-wide software pipelining as above. Infinity
// lanes blend (q at ∞ → p unchanged; p at ∞ → q); equal-x lanes
// resolve through the exact scalar path.
static void v_jac_add4(Jac8 p[4], const Jac8 q[4], const Ctx52 &c52,
                       const FieldCtx &f) {
    __mmask8 copy[4], gen[4], hz[4];
    Fp8 z1z1[4], z2z2[4], u1[4], u2[4], s1[4], s2[4], h[4], rr[4],
        i2[4], j[4], v[4], t0[4], x3[4], y3[4], z3[4], s1j[4], zz[4];
    for (int t = 0; t < 4; ++t) {
        __mmask8 act = (__mmask8)~v_is_zero5(q[t].z);
        __mmask8 pinf = v_is_zero5(p[t].z);
        copy[t] = (__mmask8)(act & pinf);
        gen[t] = (__mmask8)(act & ~pinf);
    }
    VJ4(v_mont_mul(z1z1[t], p[t].z, p[t].z, c52))
    VJ4(v_mont_mul(z2z2[t], q[t].z, q[t].z, c52))
    VJ4(v_mont_mul(u1[t], p[t].x, z2z2[t], c52))
    VJ4(v_mont_mul(u2[t], q[t].x, z1z1[t], c52))
    VJ4(v_mont_mul(s1[t], p[t].y, q[t].z, c52))
    VJ4(v_mont_mul(s1[t], s1[t], z2z2[t], c52))
    VJ4(v_mont_mul(s2[t], q[t].y, p[t].z, c52))
    VJ4(v_mont_mul(s2[t], s2[t], z1z1[t], c52))
    VJ4(v_sub_mod(h[t], u2[t], u1[t], c52))
    VJ4(v_sub_mod(rr[t], s2[t], s1[t], c52))
    VJ4(hz[t] = (__mmask8)(v_is_zero5(h[t]) & gen[t]))
    VJ4(v_add_mod(rr[t], rr[t], rr[t], c52))
    VJ4(v_add_mod(i2[t], h[t], h[t], c52))
    VJ4(v_mont_mul(i2[t], i2[t], i2[t], c52))
    VJ4(v_mont_mul(j[t], h[t], i2[t], c52))
    VJ4(v_mont_mul(v[t], u1[t], i2[t], c52))
    VJ4(v_mont_mul(x3[t], rr[t], rr[t], c52))
    VJ4(v_sub_mod(x3[t], x3[t], j[t], c52))
    VJ4(v_sub_mod(x3[t], x3[t], v[t], c52))
    VJ4(v_sub_mod(x3[t], x3[t], v[t], c52))
    VJ4(v_sub_mod(t0[t], v[t], x3[t], c52))
    VJ4(v_mont_mul(t0[t], t0[t], rr[t], c52))
    VJ4(v_mont_mul(s1j[t], s1[t], j[t], c52))
    VJ4(v_add_mod(s1j[t], s1j[t], s1j[t], c52))
    VJ4(v_sub_mod(y3[t], t0[t], s1j[t], c52))
    VJ4(v_add_mod(zz[t], p[t].z, q[t].z, c52))
    VJ4(v_mont_mul(zz[t], zz[t], zz[t], c52))
    VJ4(v_sub_mod(zz[t], zz[t], z1z1[t], c52))
    VJ4(v_sub_mod(zz[t], zz[t], z2z2[t], c52))
    VJ4(v_mont_mul(z3[t], zz[t], h[t], c52))
    for (int t = 0; t < 4; ++t) {
        if (!hz[t]) continue;
        for (int l = 0; l < 8; ++l) {
            if (!((hz[t] >> l) & 1)) continue;
            JacPoint pl, ql, res;
            jac_from_lane(p[t], l, pl, c52, f);
            jac_from_lane(q[t], l, ql, c52, f);
            jac_add(res, pl, ql, f);
            Jac8 tmp;
            tmp.x = x3[t];
            tmp.y = y3[t];
            tmp.z = z3[t];
            jac_to_lane(tmp, l, res, c52, f);
            x3[t] = tmp.x;
            y3[t] = tmp.y;
            z3[t] = tmp.z;
        }
    }
    for (int t = 0; t < 4; ++t) {
        v_blend5(p[t].x, gen[t], x3[t]);
        v_blend5(p[t].y, gen[t], y3[t]);
        v_blend5(p[t].z, gen[t], z3[t]);
        v_blend5(p[t].x, copy[t], q[t].x);
        v_blend5(p[t].y, copy[t], q[t].y);
        v_blend5(p[t].z, copy[t], q[t].z);
    }
}
#undef VJ4

// Batched independent affine pair sums: for each i < pairs, compute
// entry[heads[i]] + entry[heads[i]+1] into S.pox/S.poy with
// S.kind[i] ∈ {0 add, 1 doubling, 2 cancel-to-∞} — the batch-affine
// primitive (dual den chains, one inversion per 4096-pair batch) with
// the pairing and merge left to the caller. The multi-column kernel
// drives this per bucket-range tile so a tile's entries stay
// L2-resident across ALL its levels, where the monolithic
// level_pass_ifma above (g1_msm's committed serial path, and the
// oracle the multi kernel is measured against) re-streams the whole
// working set once per level. Exact: per-pair dinv is exactly 1/den
// regardless of batch grouping.
static void affine_pairs_ifma(const FieldCtx &f, const Ctx52 &c52,
                              const u64 *ax52, const u64 *ay52,
                              long pairs, IfmaScratch &S) {
    const long TILE = 4096;
    std::vector<long> &heads = S.heads;
    std::vector<Fp8> &prefv = S.prefv, &denv = S.denv, &axv = S.axv,
                     &ayv = S.ayv, &bxv = S.bxv, &byv = S.byv;
    std::vector<unsigned char> &kind = S.kind;
    std::memset(kind.data(), 0, pairs);
    u64 one52[5];
    fp_to52(c52.c_in, one52);
    const __m512i vzero = _mm512_setzero_si512();
    std::vector<u64> &pox = S.pox, &poy = S.poy;

    for (long tp0 = 0; tp0 < pairs; tp0 += TILE) {
        const long tpairs = (TILE < pairs - tp0) ? TILE : pairs - tp0;
        const long nblk = (tpairs + 7) / 8;
        Fp8 run[2];
        for (int ch = 0; ch < 2; ++ch)
            for (int i = 0; i < 5; ++i)
                run[ch].l[i] = _mm512_set1_epi64((long long)one52[i]);

        // pass 1 (tile): gather head/tail coords, den = xB − xA,
        // per-lane chains; saved state indexed tile-locally
        for (long b = 0; b < nblk; ++b) {
            const long p0 = tp0 + 8 * b;
            int cnt = (int)((8 > tpairs - 8 * b) ? tpairs - 8 * b : 8);
            alignas(64) long long hoff[8];
            for (int l = 0; l < 8; ++l) {
                long h = (l < cnt) ? heads[p0 + l] : heads[p0];
                hoff[l] = 5 * h;
            }
            const __m512i hv = _mm512_load_si512((const void *)hoff);
            const __m512i tv = _mm512_add_epi64(hv, _mm512_set1_epi64(5));
            Fp8 Ax, Ay, Bx, By, den;
            vgather5(Ax, ax52, hv);
            vgather5(Ay, ay52, hv);
            vgather5(Bx, ax52, tv);
            vgather5(By, ay52, tv);
            v_sub_mod(den, Bx, Ax, c52);
            __m512i nz = den.l[0];
            for (int i = 1; i < 5; ++i) nz = _mm512_or_si512(nz, den.l[i]);
            __mmask8 zl = _mm512_cmpeq_epi64_mask(nz, vzero);
            if (cnt < 8) zl = (__mmask8)(zl | (0xFF << cnt));
            if (zl) {
                u64 dl[5][8], ayl[5][8], byl[5][8];
                for (int i = 0; i < 5; ++i) {
                    _mm512_storeu_si512((void *)dl[i], den.l[i]);
                    _mm512_storeu_si512((void *)ayl[i], Ay.l[i]);
                    _mm512_storeu_si512((void *)byl[i], By.l[i]);
                }
                for (int l = 0; l < 8; ++l) {
                    if (!((zl >> l) & 1)) continue;
                    u64 t[5];
                    if (l >= cnt) {
                        std::memcpy(t, one52, 40);
                    } else {
                        Fp aY, bY, sy;
                        u64 a5[5] = {ayl[0][l], ayl[1][l], ayl[2][l],
                                     ayl[3][l], ayl[4][l]};
                        u64 b5[5] = {byl[0][l], byl[1][l], byl[2][l],
                                     byl[3][l], byl[4][l]};
                        fp_from52(a5, aY);
                        fp_from52(b5, bY);
                        add_mod(sy, aY, bY, f);
                        if (is_zero_fp(sy)) {
                            kind[p0 + l] = 2;
                            std::memcpy(t, one52, 40);
                        } else {
                            kind[p0 + l] = 1;
                            Fp dd;
                            add_mod(dd, aY, aY, f);
                            fp_to52(dd, t);
                        }
                    }
                    for (int i = 0; i < 5; ++i) dl[i][l] = t[i];
                }
                v_load_lanes(den, dl);
            }
            const int ch = (int)(b & 1);
            prefv[b] = run[ch];
            denv[b] = den;
            axv[b] = Ax;
            ayv[b] = Ay;
            bxv[b] = Bx;
            byv[b] = By;
            v_mont_mul(run[ch], run[ch], den, c52);
        }

        // tile inversion: both chains' lane totals → ONE mont_inv
        Fp8 inv_vec[2];
        {
            Fp lane_tot[16], pre[16], inv_lane[16];
            u64 lanes[2][5][8];
            for (int ch = 0; ch < 2; ++ch)
                for (int i = 0; i < 5; ++i)
                    _mm512_storeu_si512((void *)lanes[ch][i],
                                        run[ch].l[i]);
            for (int jj = 0; jj < 16; ++jj) {
                int ch = jj >> 3, l = jj & 7;
                u64 t[5] = {lanes[ch][0][l], lanes[ch][1][l],
                            lanes[ch][2][l], lanes[ch][3][l],
                            lanes[ch][4][l]};
                from_w52(lane_tot[jj], t, c52, f);
            }
            Fp acc = f.one;
            for (int jj = 0; jj < 16; ++jj) {
                pre[jj] = acc;
                mont_mul(acc, acc, lane_tot[jj], f);
            }
            Fp tinv;
            mont_inv(tinv, acc, f);
            for (int jj = 15; jj >= 0; --jj) {
                mont_mul(inv_lane[jj], tinv, pre[jj], f);
                mont_mul(tinv, tinv, lane_tot[jj], f);
            }
            u64 t[5];
            for (int jj = 0; jj < 16; ++jj) {
                int ch = jj >> 3, l = jj & 7;
                to_w52(t, inv_lane[jj], c52, f);
                for (int i = 0; i < 5; ++i) lanes[ch][i][l] = t[i];
            }
            for (int ch = 0; ch < 2; ++ch)
                v_load_lanes(inv_vec[ch], lanes[ch]);
        }

        // pass 2 (tile, backward): unwind chains, evaluate the adds
        for (long b = nblk - 1; b >= 0; --b) {
            const long p0 = tp0 + 8 * b;
            int cnt = (int)((8 > tpairs - 8 * b) ? tpairs - 8 * b : 8);
            const int ch = (int)(b & 1);
            Fp8 dinv, num;
            v_mont_mul(dinv, inv_vec[ch], prefv[b], c52);
            v_mont_mul(inv_vec[ch], inv_vec[ch], denv[b], c52);
            const Fp8 &Ax = axv[b], &Ay = ayv[b];
            const Fp8 &Bx = bxv[b], &By = byv[b];
            v_sub_mod(num, By, Ay, c52);
            bool patch = false;
            for (int l = 0; l < cnt; ++l)
                if (kind[p0 + l] == 1) patch = true;
            if (patch) {
                u64 lanes[5][8], axl[5][8];
                for (int i = 0; i < 5; ++i) {
                    _mm512_storeu_si512((void *)lanes[i], num.l[i]);
                    _mm512_storeu_si512((void *)axl[i], Ax.l[i]);
                }
                for (int l = 0; l < cnt; ++l) {
                    if (kind[p0 + l] != 1) continue;
                    u64 a5[5] = {axl[0][l], axl[1][l], axl[2][l],
                                 axl[3][l], axl[4][l]};
                    Fp aX, sq, n3;
                    fp_from52(a5, aX);
                    mont_sqr(sq, aX, f);
                    mont_mul(sq, sq, c52.c_out, f);
                    add_mod(n3, sq, sq, f);
                    add_mod(n3, n3, sq, f);
                    u64 t[5];
                    fp_to52(n3, t);
                    for (int i = 0; i < 5; ++i) lanes[i][l] = t[i];
                }
                v_load_lanes(num, lanes);
            }
            Fp8 lam, x3, y3, t0;
            v_mont_mul(lam, num, dinv, c52);
            v_mont_mul(x3, lam, lam, c52);
            v_sub_mod(x3, x3, Ax, c52);
            v_sub_mod(x3, x3, Bx, c52);
            v_sub_mod(t0, Ax, x3, c52);
            v_mont_mul(y3, lam, t0, c52);
            v_sub_mod(y3, y3, Ay, c52);
            alignas(64) long long ooff[8];
            for (int l = 0; l < 8; ++l)
                ooff[l] = 5 * (p0 + ((l < cnt) ? l : cnt - 1));
            const __m512i ov = _mm512_load_si512((const void *)ooff);
            __mmask8 live = (__mmask8)((1u << cnt) - 1);
            for (int i = 0; i < 5; ++i) {
                _mm512_mask_i64scatter_epi64(
                    pox.data(), live,
                    _mm512_add_epi64(ov, _mm512_set1_epi64(i)), x3.l[i],
                    8);
                _mm512_mask_i64scatter_epi64(
                    poy.data(), live,
                    _mm512_add_epi64(ov, _mm512_set1_epi64(i)), y3.l[i],
                    8);
            }
        }
    }

}

// Bucket-weighted suffix telescope of ONE column's window, 32 groups
// wide (8 IFMA lanes × 4 software-pipelined chain blocks): the
// column's ``half`` buckets split into 32 contiguous groups whose
// local telescopes (run += S_j; tot += run) are independent chains —
// the parallelism a single serial Pippenger reduction cannot expose.
// 8 lanes alone leave the loop bound on one chain's v_mont_mul
// LATENCY; 4 chain blocks per step keep the multipliers fed. D is the
// dense per-bucket sum array (10 u64 per bucket: x | y, w-domain),
// ``bitmap`` its occupancy. Result (s-domain Jacobian) = Σ_b b·S_b.
static void reduce_column_ifma(const FieldCtx &f, const Ctx52 &c52,
                               const u64 *D, const u64 *bitmap,
                               long half, JacPoint &out_sum) {
    const long G = half / 32;
    Jac8 run[4], tot[4];
    const __m512i zero = _mm512_setzero_si512();
    for (int t = 0; t < 4; ++t)
        for (int i = 0; i < 5; ++i) {
            run[t].x.l[i] = run[t].y.l[i] = run[t].z.l[i] = zero;
            tot[t].x.l[i] = tot[t].y.l[i] = tot[t].z.l[i] = zero;
        }
    u64 one52[5];
    fp_to52(c52.c_in, one52);
    Fp8 onev;
    for (int i = 0; i < 5; ++i)
        onev.l[i] = _mm512_set1_epi64((long long)one52[i]);
    for (long j = G; j >= 1; --j) {
        __mmask8 present[4];
        Fp8 sx[4], sy[4];
        for (int t = 0; t < 4; ++t) {
            alignas(64) long long off[8];
            __mmask8 pm = 0;
            for (int g = 0; g < 8; ++g) {
                long b = (long)(t * 8 + g) * G + j;
                if (bitmap[b >> 6] & (1ULL << (b & 63)))
                    pm = (__mmask8)(pm | (1u << g));
                off[g] = 10 * b;
            }
            present[t] = pm;
            const __m512i ov = _mm512_load_si512((const void *)off);
            vgather5_mask(sx[t], D, ov, pm);
            vgather5_mask(sy[t], D + 5, ov, pm);
        }
        v_jac_add_mixed4(run, sx, sy, present, c52, f, onev);
        v_jac_add4(tot, run, c52, f);
    }
    out_sum.z = Fp{{0, 0, 0, 0}};
    for (int t = 0; t < 4; ++t)
        for (int g = 0; g < 8; ++g) {
            JacPoint tt, r;
            jac_from_lane(tot[t], g, tt, c52, f);
            jac_from_lane(run[t], g, r, c52, f);
            jac_add(out_sum, out_sum, tt, f);
            jac_add_small_mul(out_sum, r, (u64)((long)(t * 8 + g) * G),
                              f);
        }
}

// Sparse form of the telescope: walk only the OCCUPIED buckets (bitmap
// scan, descending) with the gap-skipping serial chain — cheaper than
// G masked vector steps when a window populated few buckets (small
// columns, 0/1 selector columns).
static void reduce_column_sparse_ifma(const FieldCtx &f, const Ctx52 &c52,
                                      const u64 *D, const u64 *bitmap,
                                      long half, JacPoint &out_sum) {
    JacPoint running;
    running.z = Fp{{0, 0, 0, 0}};
    out_sum.z = Fp{{0, 0, 0, 0}};
    long prev_b = half + 1;
    for (long wq = half >> 6; wq >= 0; --wq) {
        u64 bits = bitmap[wq];
        while (bits) {
            int hi = 63 - __builtin_clzll(bits);
            bits &= ~(1ULL << hi);
            long b = (wq << 6) + hi;
            jac_add_small_mul(out_sum, running, (u64)(prev_b - b - 1), f);
            AffPt q;
            from_w52(q.x, &D[10 * b], c52, f);
            from_w52(q.y, &D[10 * b + 5], c52, f);
            jac_add_mixed(running, running, q, f);
            jac_add(out_sum, out_sum, running, f);
            prev_b = b;
        }
    }
    jac_add_small_mul(out_sum, running, (u64)(prev_b - 1), f);
}
#endif  // PN_IFMA

void g1_msm_multi(const u64 *mod_limbs, const u64 *bases,
                  const u64 *scalars, const unsigned char *flips,
                  long n, long K, u64 *out) {
    FieldCtx f = make_ctx(mod_limbs);
    if (n <= 0 || K <= 0) {
        if (K > 0) std::memset(out, 0, 64 * (size_t)K);
        return;
    }
#ifdef PN_IFMA
    const bool use_ifma = !std::getenv("PN_NO_IFMA") && ifma_available() &&
                          v_mul_selftest(f);
    Ctx52 c52;
    if (use_ifma) c52 = make_ctx52(f);
#endif
    int c = 4;
    if (n > 32) c = 8;
    if (n > 1024) c = 12;
    if (n > 131072) c = 15;  // g1_msm's ladder (the r4 grid)
    if (n > 600000) c = 16;  // the tiled levels + 32-chain vector
                             // reduce move the multi optimum UP at
                             // 2^20 (r8 grid on the IFMA box);
                             // PN_MSM_C_MULTI / PN_MSM_C override
    if (const char *cenv = std::getenv("PN_MSM_C_MULTI")) {
        int cv = std::atoi(cenv);
        if (cv >= 2 && cv <= 20) c = cv;
    } else if (const char *cenv = std::getenv("PN_MSM_C")) {
        int cv = std::atoi(cenv);
        if (cv >= 2 && cv <= 20) c = cv;
    }
    const long half = 1L << (c - 1);
    const int windows = (256 + c - 1) / c + 1;

    std::vector<AffPt> pts(n);
    std::vector<unsigned char> finite(n);
    long n_finite = 0;
    for (long i = 0; i < n; ++i) {
        Fp x, y;
        std::memcpy(x.v, bases + 8 * i, 32);
        std::memcpy(y.v, bases + 8 * i + 4, 32);
        bool inf = is_zero_fp(x) && is_zero_fp(y);
        finite[i] = !inf;
        if (!inf) {
            to_mont(pts[i].x, x, f);
            to_mont(pts[i].y, y, f);
            ++n_finite;
        }
    }
    std::vector<JacPoint> totals(K);
    for (long k = 0; k < K; ++k) totals[k].z = Fp{{0, 0, 0, 0}};
    if (!n_finite) {
        std::memset(out, 0, 64 * (size_t)K);
        return;
    }

    const bool dbg = std::getenv("PN_MSM_DEBUG") != nullptr;
    long dbg_vec_cols = 0, dbg_scal_cols = 0;
    double t_conv = 0, t_sort = 0, t_levels = 0, t_reduce = 0;
    auto now = [] { return std::chrono::steady_clock::now(); };
    auto secs = [](auto a, auto b) {
        return std::chrono::duration<double>(b - a).count();
    };
    auto tc0 = now();

#ifdef PN_IFMA
    // shared per-point table, converted ONCE for all K columns:
    // 15 w-domain limbs per point — x | y | −y (signed digits and
    // per-column flips both index the negated copy)
    std::unique_ptr<u64[]> p15;
    if (use_ifma) {
        p15.reset(new u64[15 * (size_t)n]);
        for (long i = 0; i < n; ++i) {
            if (!finite[i]) continue;
            to_w52(&p15[15 * (size_t)i], pts[i].x, c52, f);
            to_w52(&p15[15 * (size_t)i + 5], pts[i].y, c52, f);
            Fp yn;
            neg_mod(yn, pts[i].y, f);
            to_w52(&p15[15 * (size_t)i + 10], yn, c52, f);
        }
    }
#endif
    t_conv += secs(tc0, now());

    // columns are processed in chunks of KB — the window pass (recode,
    // counting sort, levels) is shared WITHIN a chunk, while the base
    // parse + w-domain conversion are amortized over ALL K columns.
    // Measured on the r8 IFMA box, the cross-column sharing inside a
    // window pass is NET NEGATIVE: the chunk·n working set's cache/TLB
    // cost exceeds the shared-read win at every size tried (2^20 K=4
    // aggregate: 1.59x at KB=1 vs 1.55x/1.52x at KB=2/4; K=8
    // monolithic was 1.38x), so the default processes one column per
    // window sweep and the K-column win comes from the shared
    // conversions + the tiled levels + the vector reduce. PN_MSM_KB
    // re-enables wider sharing for boxes where the balance differs.
    long KB = 1;
    if (const char *kbenv = std::getenv("PN_MSM_KB")) {
        long kv = std::atol(kbenv);
        if (kv >= 1 && kv <= 64) KB = kv;
    }
    if (KB > K) KB = K;
    const size_t mcap = (size_t)n_finite * (size_t)KB;
    std::unique_ptr<int32_t[]> abid_own, nbid_own;
    int32_t *abid = nullptr, *nbid = nullptr;
#ifdef PN_IFMA
    std::unique_ptr<u64[]> x52_own, y52_own;
    u64 *x52 = nullptr, *y52 = nullptr;
    std::unique_ptr<u64[]> Dbuf, Dbitmap;
    IfmaScratch ifma_scratch;
    const bool vec_reduce_ok = use_ifma && half >= 256 &&
                               (half % 32) == 0;
    std::unique_ptr<long[]> bstart;
    if (use_ifma) {
        x52_own.reset(new u64[5 * mcap]);
        y52_own.reset(new u64[5 * mcap]);
        x52 = x52_own.get();
        y52 = y52_own.get();
        Dbuf.reset(new u64[10 * (size_t)(half + 1)]);
        Dbitmap.reset(new u64[(size_t)(half >> 6) + 2]);
        bstart.reset(new long[(size_t)KB * half + 2]);
    }
#endif
    // Fp working set, allocated only when a scalar level/tail runs
    std::unique_ptr<Fp[]> ax_own, ay_own, nx_own, ny_own;
    Fp *ax = nullptr, *ay = nullptr, *nxp = nullptr, *nyp = nullptr;
    auto ensure_fp = [&]() {
        if (!ax_own) {
            ax_own.reset(new Fp[mcap]);
            ay_own.reset(new Fp[mcap]);
            nx_own.reset(new Fp[mcap]);
            ny_own.reset(new Fp[mcap]);
            ax = ax_own.get();
            ay = ay_own.get();
            nxp = nx_own.get();
            nyp = ny_own.get();
        }
    };
    bool scalar_path = true;
#ifdef PN_IFMA
    scalar_path = !use_ifma;
#endif
    std::vector<unsigned char> role;
    std::vector<Fp> dens, prefix;
    if (scalar_path) {  // the Fp pairing-level machinery
        ensure_fp();
        abid_own.reset(new int32_t[mcap]);
        nbid_own.reset(new int32_t[mcap]);
        abid = abid_own.get();
        nbid = nbid_own.get();
        role.resize(mcap);
        dens.reserve(mcap / 2 + 1);
        prefix.reserve(mcap / 2 + 1);
    }

    std::vector<long> counts((size_t)KB * half + 1);
    std::vector<unsigned char> carry((size_t)KB * n);
    std::unique_ptr<int32_t[]> dcur(new int32_t[(size_t)KB * n]);

    // windows ascend (the on-the-fly recode's carries flow LSB→MSB);
    // each window total joins its column shifted by c·w doublings —
    // a few thousand doublings per call, noise next to the levels
    for (long k0 = 0; k0 < K; k0 += KB) {
    const long Kc = (KB < K - k0) ? KB : K - k0;
    std::memset(carry.data(), 0, (size_t)Kc * n);
    for (int w = 0; w < windows; ++w) {
        auto ts0 = now();
        std::fill(counts.begin(), counts.end(), 0);
        long m = 0;
        const long bit0 = (long)w * c;
        for (long k = 0; k < Kc; ++k) {
            const u64 *sc = scalars + 4 * (size_t)n * (k0 + k);
            unsigned char *cy = &carry[(size_t)k * n];
            int32_t *dk = &dcur[(size_t)k * n];
            for (long i = 0; i < n; ++i) {
                if (!finite[i]) {
                    dk[i] = 0;
                    continue;
                }
                u64 raw = 0;
                if (bit0 < 256) {
                    int word = (int)(bit0 / 64), off = (int)(bit0 % 64);
                    raw = sc[4 * i + word] >> off;
                    if (off && word + 1 < 4)
                        raw |= sc[4 * i + word + 1] << (64 - off);
                    raw &= ((u64)1 << c) - 1;
                }
                raw += cy[i];
                int32_t d;
                if (raw > (u64)half) {
                    d = (int32_t)raw - (int32_t)(1L << c);
                    cy[i] = 1;
                } else {
                    d = (int32_t)raw;
                    cy[i] = 0;
                }
                dk[i] = d;
                if (d) {
                    ++counts[(size_t)k * half + (d < 0 ? -d : d)];
                    ++m;
                }
            }
        }
        if (!m) continue;
        long acc_off = 0;
        for (long b = 1; b <= (long)Kc * half; ++b) {
            long cnt = counts[b];
            counts[b] = acc_off;
            acc_off += cnt;
        }
#ifdef PN_IFMA
        if (use_ifma) {
            // bucket start offsets (pre-placement prefix) + sentinel —
            // the tiled levels and the dense reduce read ranges from
            // here instead of carrying a per-entry bucket-id array
            std::memcpy(bstart.get(), counts.data(),
                        sizeof(long) * ((size_t)Kc * half + 1));
            bstart[(size_t)Kc * half + 1] = m;
        }
#endif
        // placement, i-outer / k-inner: ONE walk of the shared base
        // table covers all K columns' bucket placements — point i's
        // coordinates are read once and feed up to K placements while
        // they sit in L1 (the amortized gather; serial calls re-stream
        // the whole table once per column per window).
        for (long i = 0; i < n; ++i) {
            if (!finite[i]) continue;
#ifdef PN_IFMA
            const u64 *src = use_ifma ? &p15[15 * (size_t)i] : nullptr;
#endif
            for (long k = 0; k < Kc; ++k) {
                int32_t d = dcur[(size_t)k * n + i];
                if (!d) continue;
                long b = d < 0 ? -d : d;
                long pos = counts[(size_t)k * half + b]++;
                int neg = d < 0;
                if (flips && flips[(size_t)(k0 + k) * n + i]) neg ^= 1;
#ifdef PN_IFMA
                if (use_ifma) {
                    std::memcpy(&x52[5 * (size_t)pos], src, 40);
                    std::memcpy(&y52[5 * (size_t)pos],
                                src + 5 + 5 * neg, 40);
                    continue;
                }
#endif
                abid[pos] = (int32_t)((size_t)k * half + b);
                ax[pos] = pts[i].x;
                if (neg) neg_mod(ay[pos], pts[i].y, f);
                else ay[pos] = pts[i].y;
            }
        }
        t_sort += secs(ts0, now());

#ifdef PN_IFMA
        if (use_ifma) {
            // Per-column bucket-range-tiled levels: a tile of TB
            // buckets' entries (~TB·avg-count rows, ~1-2 MB dense)
            // stays cache-resident across ALL of its pairing levels —
            // a monolithic level pass re-streams the whole K·n
            // working set once per level instead. Survivors drop
            // straight into the dense per-bucket array D that feeds
            // the 32-group vector telescope; no per-entry bucket-id
            // array, no merge pass, no ping-pong copies.
            const long TB = 256;
            std::vector<long> bloc(TB), bcnt(TB);
            u64 *D = Dbuf.get();
            for (long k = 0; k < Kc; ++k) {
                auto tl0 = now();
                const size_t kbase = (size_t)k * half;
                std::memset(Dbitmap.get(), 0,
                            8 * ((size_t)(half >> 6) + 2));
                long occ = 0;
                for (long tb0 = 0; tb0 < half; tb0 += TB) {
                    const long nb = (TB < half - tb0) ? TB : half - tb0;
                    const long tstart = bstart[kbase + tb0 + 1];
                    const long tend = bstart[kbase + tb0 + nb + 1];
                    if (tend == tstart) continue;
                    for (long t = 0; t < nb; ++t) {
                        bloc[t] = bstart[kbase + tb0 + t + 1];
                        bcnt[t] = bstart[kbase + tb0 + t + 2] - bloc[t];
                    }
                    ifma_scratch.ensure((tend - tstart) / 2 + 8);
                    while (true) {
                        long pairs = 0;
                        for (long t = 0; t < nb; ++t) {
                            long pb = bcnt[t] >> 1;
                            for (long j2 = 0; j2 < pb; ++j2)
                                ifma_scratch.heads[pairs + j2] =
                                    bloc[t] + 2 * j2;
                            pairs += pb;
                        }
                        if (!pairs) break;
                        affine_pairs_ifma(f, c52, x52, y52, pairs,
                                          ifma_scratch);
                        // bucket-aware in-place compaction: survivors
                        // (pair sums + odd tails) pack forward; writes
                        // never pass reads (survivors ≤ entries)
                        long pi = 0, wr = bloc[0];
                        for (long t = 0; t < nb; ++t) {
                            const long cnt = bcnt[t], pb = cnt >> 1;
                            const long ns = wr;
                            for (long j2 = 0; j2 < pb; ++j2, ++pi) {
                                if (ifma_scratch.kind[pi] == 2)
                                    continue;
                                std::memcpy(&x52[5 * wr],
                                            &ifma_scratch.pox[5 * pi],
                                            40);
                                std::memcpy(&y52[5 * wr],
                                            &ifma_scratch.poy[5 * pi],
                                            40);
                                ++wr;
                            }
                            if (cnt & 1) {
                                long src2 = bloc[t] + cnt - 1;
                                if (src2 != wr) {
                                    std::memcpy(&x52[5 * wr],
                                                &x52[5 * src2], 40);
                                    std::memcpy(&y52[5 * wr],
                                                &y52[5 * src2], 40);
                                }
                                ++wr;
                            }
                            bloc[t] = ns;
                            bcnt[t] = wr - ns;
                        }
                    }
                    for (long t = 0; t < nb; ++t) {
                        if (!bcnt[t]) continue;
                        long b = tb0 + t + 1;
                        std::memcpy(&D[10 * b], &x52[5 * bloc[t]], 40);
                        std::memcpy(&D[10 * b + 5], &y52[5 * bloc[t]],
                                    40);
                        Dbitmap[b >> 6] |= 1ULL << (b & 63);
                        ++occ;
                    }
                }
                t_levels += secs(tl0, now());
                auto tr0 = now();
                if (occ) {
                    JacPoint sum;
                    if (vec_reduce_ok && occ * 4 >= half) {
                        reduce_column_ifma(f, c52, D, Dbitmap.get(),
                                           half, sum);
                        if (dbg) ++dbg_vec_cols;
                    } else {
                        reduce_column_sparse_ifma(f, c52, D,
                                                  Dbitmap.get(), half,
                                                  sum);
                        if (dbg) ++dbg_scal_cols;
                    }
                    if (!is_zero_fp(sum.z)) {
                        // shift into place: window w weighs 2^{c·w}
                        for (long d2 = 0; d2 < (long)c * w; ++d2)
                            jac_double(sum, sum, f);
                        jac_add(totals[k0 + k], totals[k0 + k], sum, f);
                    }
                }
                t_reduce += secs(tr0, now());
            }
            continue;  // next window
        }
#endif

        auto tl0 = now();
        // scalar fallback (no IFMA): level-by-level batch-affine
        // segment sums over ALL K columns at once (bucket keys are
        // column-disjoint, so segments never cross columns and one
        // inversion serves K columns' pairs)
        while (true) {
            long pairs = 0;
            for (long i = 0; i < m;) {
                if (i + 1 < m && abid[i + 1] == abid[i]) {
                    role[i] = 1;
                    role[i + 1] = 2;
                    ++pairs;
                    i += 2;
                } else {
                    role[i] = 0;
                    ++i;
                }
            }
            if (!pairs) break;
            dens.clear();
            prefix.clear();
            Fp run = f.one;
            std::vector<unsigned char> kind;
            kind.reserve(pairs);
            for (long i = 0; i < m; ++i) {
                if (role[i] != 1) continue;
                Fp d;
                sub_mod(d, ax[i + 1], ax[i], f);
                if (is_zero_fp(d)) {
                    Fp sy;
                    add_mod(sy, ay[i], ay[i + 1], f);
                    if (is_zero_fp(sy)) {
                        kind.push_back(2);
                        d = f.one;
                    } else {
                        kind.push_back(1);
                        add_mod(d, ay[i], ay[i], f);
                    }
                } else kind.push_back(0);
                dens.push_back(d);
                prefix.push_back(run);
                mont_mul(run, run, d, f);
            }
            Fp inv;
            mont_inv(inv, run, f);
            long n_out = m - pairs;
            for (long pi = 0; pi < pairs; ++pi)
                if (kind[pi] == 2) --n_out;
            long write = n_out;
            long pi = pairs - 1;
            for (long i = m - 1; i >= 0; --i) {
                if (role[i] == 2) continue;
                if (role[i] == 1) {
                    Fp dinv;
                    mont_mul(dinv, inv, prefix[pi], f);
                    mont_mul(inv, inv, dens[pi], f);
                    if (kind[pi] != 2) {
                        long a = i, b = i + 1;
                        Fp lam, num, x3, y3;
                        if (kind[pi] == 1) {
                            mont_sqr(num, ax[a], f);
                            Fp n3;
                            add_mod(n3, num, num, f);
                            add_mod(num, n3, num, f);
                        } else {
                            sub_mod(num, ay[b], ay[a], f);
                        }
                        mont_mul(lam, num, dinv, f);
                        mont_sqr(x3, lam, f);
                        sub_mod(x3, x3, ax[a], f);
                        sub_mod(x3, x3, ax[b], f);
                        sub_mod(y3, ax[a], x3, f);
                        mont_mul(y3, y3, lam, f);
                        sub_mod(y3, y3, ay[a], f);
                        --write;
                        nxp[write] = x3;
                        nyp[write] = y3;
                        nbid[write] = abid[i];
                    }
                    --pi;
                } else {
                    --write;
                    nxp[write] = ax[i];
                    nyp[write] = ay[i];
                    nbid[write] = abid[i];
                }
            }
            m = n_out;
            std::swap(ax, nxp);
            std::swap(ay, nyp);
            std::swap(abid, nbid);
        }
        t_levels += secs(tl0, now());

        auto tr0 = now();
        // per-column bucket reduction (scalar path): survivors sit
        // ascending by (column, bucket); walk columns from the top
        // with the gap-skipping serial telescope.
        long i_top = m - 1;
        for (long k = Kc - 1; k >= 0; --k) {
            const long base = k * half;
            long lo = i_top;
            while (lo >= 0 && abid[lo] > base) --lo;
            // column k's survivors are (lo, i_top]
            if (lo == i_top) continue;
            JacPoint sum;
            sum.z = Fp{{0, 0, 0, 0}};
            JacPoint running;
            running.z = Fp{{0, 0, 0, 0}};
            long prev_b = half + 1;
            for (long i = i_top; i > lo; --i) {
                long b = abid[i] - base;
                jac_add_small_mul(sum, running, (u64)(prev_b - b - 1),
                                  f);
                AffPt q;
                q.x = ax[i];
                q.y = ay[i];
                jac_add_mixed(running, running, q, f);
                jac_add(sum, sum, running, f);
                prev_b = b;
            }
            jac_add_small_mul(sum, running, (u64)(prev_b - 1), f);
            if (dbg) ++dbg_scal_cols;
            if (!is_zero_fp(sum.z)) {
                // shift into place: window w weighs 2^{c·w}
                for (long d = 0; d < (long)c * w; ++d)
                    jac_double(sum, sum, f);
                jac_add(totals[k0 + k], totals[k0 + k], sum, f);
            }
            i_top = lo;
        }
        t_reduce += secs(tr0, now());
    }
    }  // column chunk

    if (dbg) {
#ifdef PN_IFMA
        std::fprintf(stderr, "g1_msm_multi ifma=%d\n", (int)use_ifma);
#endif
        std::fprintf(stderr,
                     "g1_msm_multi n=%ld K=%ld c=%d: conv %.2fs sort "
                     "%.2fs levels %.2fs reduce %.2fs\n",
                     n, K, c, t_conv, t_sort, t_levels, t_reduce);
        std::fprintf(stderr,
                     "g1_msm_multi reduce: vec_cols=%ld scal_cols=%ld\n",
                     dbg_vec_cols, dbg_scal_cols);
    }

    for (long k = 0; k < K; ++k) {
        u64 *ok = out + 8 * (size_t)k;
        if (is_zero_fp(totals[k].z)) {
            std::memset(ok, 0, 64);
            continue;
        }
        Fp zinv, zinv2, zinv3, axx, ayy;
        mont_inv(zinv, totals[k].z, f);
        mont_sqr(zinv2, zinv, f);
        mont_mul(zinv3, zinv2, zinv, f);
        mont_mul(axx, totals[k].x, zinv2, f);
        mont_mul(ayy, totals[k].y, zinv3, f);
        from_mont(axx, axx, f);
        from_mont(ayy, ayy, f);
        std::memcpy(ok, axx.v, 32);
        std::memcpy(ok + 4, ayy.v, 32);
    }
}

// Many scalar multiples of ONE fixed affine base: out[i] = scalars[i]·B.
// 8-bit window table (32 windows x 256 entries), then one batched
// Jacobian->affine normalization. Powers the SRS ("powers of tau") setup,
// where n independent muls of G1 would otherwise dominate.
void g1_fixed_base_muls(const u64 *mod_limbs, const u64 *base_aff,
                        const u64 *scalars, long n, u64 *out) {
    FieldCtx f = make_ctx(mod_limbs);
    JacPoint base;
    std::memcpy(base.x.v, base_aff, 32);
    std::memcpy(base.y.v, base_aff + 4, 32);
    to_mont(base.x, base.x, f);
    to_mont(base.y, base.y, f);
    base.z = f.one;

    const int C = 8, WINDOWS = 32, TABLE = 1 << C;
    // table[w][j] = j * 2^{8w} * B
    std::vector<JacPoint> table((size_t)WINDOWS * TABLE);
    JacPoint win_base = base;
    for (int w = 0; w < WINDOWS; ++w) {
        JacPoint *row = &table[(size_t)w * TABLE];
        row[0].z = Fp{{0, 0, 0, 0}};
        row[1] = win_base;
        for (int j = 2; j < TABLE; ++j) jac_add(row[j], row[j - 1], win_base, f);
        if (w + 1 < WINDOWS) {
            jac_add(win_base, row[TABLE - 1], win_base, f);  // 2^{8(w+1)} B
        }
    }

    std::vector<JacPoint> res(n);
    for (long i = 0; i < n; ++i) {
        JacPoint acc;
        acc.z = Fp{{0, 0, 0, 0}};
        for (int w = 0; w < WINDOWS; ++w) {
            u64 word = scalars[4 * i + w / 8];
            u64 idx = (word >> ((w % 8) * 8)) & 0xff;
            if (idx) jac_add(acc, acc, table[(size_t)w * TABLE + idx], f);
        }
        res[i] = acc;
    }

    // batched normalization: invert all z^1 at once
    std::vector<Fp> zs(n), prefix(n);
    Fp acc = f.one;
    for (long i = 0; i < n; ++i) {
        zs[i] = is_zero_fp(res[i].z) ? f.one : res[i].z;
        prefix[i] = acc;
        mont_mul(acc, acc, zs[i], f);
    }
    Fp inv;
    mont_inv(inv, acc, f);
    for (long i = n - 1; i >= 0; --i) {
        Fp zi;
        mont_mul(zi, inv, prefix[i], f);
        mont_mul(inv, inv, zs[i], f);
        if (is_zero_fp(res[i].z)) {
            std::memset(out + 8 * i, 0, 64);
            continue;
        }
        Fp z2, z3, ax, ay;
        mont_sqr(z2, zi, f);
        mont_mul(z3, z2, zi, f);
        mont_mul(ax, res[i].x, z2, f);
        mont_mul(ay, res[i].y, z3, f);
        from_mont(ax, ax, f);
        from_mont(ay, ay, f);
        std::memcpy(out + 8 * i, ax.v, 32);
        std::memcpy(out + 8 * i + 4, ay.v, 32);
    }
}

// test shim: affine double + add through the Jacobian path
void g1_test_ops(const u64 *mod_limbs, const u64 *p_aff, const u64 *q_aff,
                 u64 *dbl_out, u64 *add_out) {
    FieldCtx f = make_ctx(mod_limbs);
    JacPoint p, q;
    std::memcpy(p.x.v, p_aff, 32);
    std::memcpy(p.y.v, p_aff + 4, 32);
    to_mont(p.x, p.x, f);
    to_mont(p.y, p.y, f);
    p.z = f.one;
    std::memcpy(q.x.v, q_aff, 32);
    std::memcpy(q.y.v, q_aff + 4, 32);
    to_mont(q.x, q.x, f);
    to_mont(q.y, q.y, f);
    q.z = f.one;
    JacPoint d, s;
    jac_double(d, p, f);
    jac_add(s, p, q, f);
    JacPoint pts[2] = {d, s};
    u64 *outs[2] = {dbl_out, add_out};
    for (int i = 0; i < 2; ++i) {
        Fp zinv, zinv2, zinv3, ax, ay;
        mont_inv(zinv, pts[i].z, f);
        mont_sqr(zinv2, zinv, f);
        mont_mul(zinv3, zinv2, zinv, f);
        mont_mul(ax, pts[i].x, zinv2, f);
        mont_mul(ay, pts[i].y, zinv3, f);
        from_mont(ax, ax, f);
        from_mont(ay, ay, f);
        std::memcpy(outs[i], ax.v, 32);
        std::memcpy(outs[i] + 4, ay.v, 32);
    }
}

// --- PLONK grand products -------------------------------------------------

// permutation grand product z for NUM_WIRES wires.
// wires: [w][i] standard form; sigma_evals likewise; shifts: per-wire
// scalars; omegas: domain elements. Writes z (n values, standard form).
// Returns 0 on success, 1 if the product fails to wrap to 1.
int perm_grand_product(const u64 *mod_limbs, const u64 *wires, int num_wires,
                       const u64 *sigma, const u64 *shifts, const u64 *omegas,
                       const u64 *beta_l, const u64 *gamma_l, long n,
                       u64 *z_out) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp beta, gamma;
    std::memcpy(beta.v, beta_l, 32);
    std::memcpy(gamma.v, gamma_l, 32);
    to_mont(beta, beta, f);
    to_mont(gamma, gamma, f);

    std::vector<Fp> numer(n), denom(n), om_m(n);
    for (long i = 0; i < n; ++i) {
        numer[i] = f.one;
        denom[i] = f.one;
        std::memcpy(om_m[i].v, omegas + 4 * i, 32);
        to_mont(om_m[i], om_m[i], f);
    }
    for (int w = 0; w < num_wires; ++w) {
        Fp kw;
        std::memcpy(kw.v, shifts + 4 * w, 32);
        to_mont(kw, kw, f);
        Fp beta_kw;
        mont_mul(beta_kw, beta, kw, f);
        const u64 *col = wires + (size_t)w * 4 * n;
        const u64 *sg = sigma + (size_t)w * 4 * n;
        for (long i = 0; i < n; ++i) {
            Fp wv, sv, t1, t2;
            std::memcpy(wv.v, col + 4 * i, 32);
            to_mont(wv, wv, f);
            const Fp &om = om_m[i];
            std::memcpy(sv.v, sg + 4 * i, 32);
            to_mont(sv, sv, f);
            mont_mul(t1, beta_kw, om, f);
            add_mod(t1, t1, wv, f);
            add_mod(t1, t1, gamma, f);
            mont_mul(numer[i], numer[i], t1, f);
            mont_mul(t2, beta, sv, f);
            add_mod(t2, t2, wv, f);
            add_mod(t2, t2, gamma, f);
            mont_mul(denom[i], denom[i], t2, f);
        }
    }
    // batch invert denom (all nonzero w.h.p.)
    std::vector<Fp> prefix(n);
    Fp acc = f.one;
    for (long i = 0; i < n; ++i) {
        prefix[i] = acc;
        mont_mul(acc, acc, denom[i], f);
    }
    Fp inv;
    mont_inv(inv, acc, f);
    std::vector<Fp> dinv(n);
    for (long i = n - 1; i >= 0; --i) {
        mont_mul(dinv[i], inv, prefix[i], f);
        mont_mul(inv, inv, denom[i], f);
    }
    Fp z = f.one;
    for (long i = 0; i < n; ++i) {
        Fp out;
        from_mont(out, z, f);
        std::memcpy(z_out + 4 * i, out.v, 32);
        Fp step;
        mont_mul(step, numer[i], dinv[i], f);
        mont_mul(z, z, step, f);
    }
    // wrap check: z after last row must be 1
    Fp z_std;
    from_mont(z_std, z, f);
    Fp one_std = {{1, 0, 0, 0}};
    for (int k = 0; k < 4; ++k)
        if (z_std.v[k] != one_std.v[k]) return 1;
    return 0;
}

// LogUp running sum phi. a_col, table, m: standard form length n.
// Returns 0 ok / 1 if the sum fails to wrap to 0.
int logup_running_sum(const u64 *mod_limbs, const u64 *a_col,
                      const u64 *table, const u64 *m_col,
                      const u64 *beta_l, long n, u64 *phi_out) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp beta;
    std::memcpy(beta.v, beta_l, 32);
    to_mont(beta, beta, f);
    std::vector<Fp> inv_a(n), inv_t(n);
    for (long i = 0; i < n; ++i) {
        Fp a, t;
        std::memcpy(a.v, a_col + 4 * i, 32);
        to_mont(a, a, f);
        add_mod(inv_a[i], a, beta, f);
        std::memcpy(t.v, table + 4 * i, 32);
        to_mont(t, t, f);
        add_mod(inv_t[i], t, beta, f);
    }
    // joint batch inversion
    std::vector<Fp> all(2 * n), prefix(2 * n);
    for (long i = 0; i < n; ++i) { all[i] = inv_a[i]; all[n + i] = inv_t[i]; }
    Fp acc = f.one;
    for (long i = 0; i < 2 * n; ++i) { prefix[i] = acc; mont_mul(acc, acc, all[i], f); }
    Fp inv;
    mont_inv(inv, acc, f);
    for (long i = 2 * n - 1; i >= 0; --i) {
        Fp r;
        mont_mul(r, inv, prefix[i], f);
        mont_mul(inv, inv, all[i], f);
        all[i] = r;
    }
    Fp phi = {{0, 0, 0, 0}};
    for (long i = 0; i < n; ++i) {
        Fp out;
        from_mont(out, phi, f);
        std::memcpy(phi_out + 4 * i, out.v, 32);
        Fp mi, term;
        std::memcpy(mi.v, m_col + 4 * i, 32);
        to_mont(mi, mi, f);
        mont_mul(term, mi, all[n + i], f);
        Fp step;
        sub_mod(step, all[i], term, f);
        add_mod(phi, phi, step, f);
    }
    return is_zero_fp(phi) ? 0 : 1;
}

// --- quotient kernel ------------------------------------------------------

// Evaluate the full PLONK constraint combination over the extended coset
// and divide by Z_H. All arrays are standard-form, length ext_n:
//   wires_e[6], z_e, zw_e, m_e, phi_e, phiw_e, fixed_e[9 in FIXED order],
//   sigma_e[6], pi_e, xs (coset points), zh_inv, l0 (zh*l0_den)
// scalars: beta, gamma, beta_lk, alpha, shifts[6]
// fixed order: q_a q_b q_c q_d q_e q_mul_ab q_mul_cd q_const t_lookup
// z-split quotient identity (r4): the degree-7 permutation constraint
// is decomposed through four partial-product advice columns
// u1 = z·f0·f1, u2 = u1·f2·f3, v1 = z(ωX)·g0·g1, v2 = v1·g2·g3 and the
// link u2·f4·f5 − v2·g4·g5, capping every term at 3 polynomial factors
// so the extension coset is 4n (see zk/plonk.py prove()).
// uv_e: 4 stacked ext arrays in [u1, u2, v1, v2] order.
void quotient_eval2(const u64 *mod_limbs, const u64 *wires_e, const u64 *z_e,
                    const u64 *zw_e, const u64 *m_e, const u64 *phi_e,
                    const u64 *phiw_e, const u64 *uv_e, const u64 *fixed_e,
                    const u64 *sigma_e, const u64 *pi_e, const u64 *xs,
                    const u64 *zh_inv_a, const u64 *l0_a, const u64 *beta_l,
                    const u64 *gamma_l, const u64 *beta_lk_l,
                    const u64 *alpha_l, const u64 *shifts_l, long ext_n,
                    u64 *t_out) {
    FieldCtx f = make_ctx(mod_limbs);
    Fp beta, gamma, beta_lk, alpha, shifts[6];
    std::memcpy(beta.v, beta_l, 32); to_mont(beta, beta, f);
    std::memcpy(gamma.v, gamma_l, 32); to_mont(gamma, gamma, f);
    std::memcpy(beta_lk.v, beta_lk_l, 32); to_mont(beta_lk, beta_lk, f);
    std::memcpy(alpha.v, alpha_l, 32); to_mont(alpha, alpha, f);
    for (int w = 0; w < 6; ++w) {
        std::memcpy(shifts[w].v, shifts_l + 4 * w, 32);
        to_mont(shifts[w], shifts[w], f);
    }
    Fp ap[9];  // ap[k] = alpha^k
    ap[0] = f.one;
    ap[1] = alpha;
    for (int k = 2; k <= 8; ++k) mont_mul(ap[k], ap[k - 1], alpha, f);

    auto load = [&](const u64 *arr, long i, Fp &out_fp) {
        std::memcpy(out_fp.v, arr + 4 * i, 32);
        to_mont(out_fp, out_fp, f);
    };

    for (long i = 0; i < ext_n; ++i) {
        Fp w[6];
        for (int k = 0; k < 6; ++k) load(wires_e + (size_t)k * 4 * ext_n, i, w[k]);
        Fp fx[9];
        for (int k = 0; k < 9; ++k) load(fixed_e + (size_t)k * 4 * ext_n, i, fx[k]);
        Fp sg[6];
        for (int k = 0; k < 6; ++k) load(sigma_e + (size_t)k * 4 * ext_n, i, sg[k]);
        Fp uv[4];
        for (int k = 0; k < 4; ++k) load(uv_e + (size_t)k * 4 * ext_n, i, uv[k]);
        Fp zi, zwi, mi, phii, phiwi, pii, xi, zhi, l0i;
        load(z_e, i, zi); load(zw_e, i, zwi); load(m_e, i, mi);
        load(phi_e, i, phii); load(phiw_e, i, phiwi); load(pi_e, i, pii);
        load(xs, i, xi); load(zh_inv_a, i, zhi); load(l0_a, i, l0i);

        // gate
        Fp gate = {{0, 0, 0, 0}}, t;
        for (int k = 0; k < 5; ++k) {
            mont_mul(t, fx[k], w[k], f);
            add_mod(gate, gate, t, f);
        }
        Fp ab, cd;
        mont_mul(ab, w[0], w[1], f);
        mont_mul(cd, w[2], w[3], f);
        mont_mul(t, fx[5], ab, f);
        add_mod(gate, gate, t, f);
        mont_mul(t, fx[6], cd, f);
        add_mod(gate, gate, t, f);
        add_mod(gate, gate, fx[7], f);
        add_mod(gate, gate, pii, f);

        // permutation wire factors fv/gv
        Fp fv[6], gv[6];
        for (int k = 0; k < 6; ++k) {
            mont_mul(fv[k], beta, shifts[k], f);
            mont_mul(fv[k], fv[k], xi, f);
            add_mod(fv[k], fv[k], w[k], f);
            add_mod(fv[k], fv[k], gamma, f);
            mont_mul(gv[k], beta, sg[k], f);
            add_mod(gv[k], gv[k], w[k], f);
            add_mod(gv[k], gv[k], gamma, f);
        }
        // link: u2·f4·f5 − v2·g4·g5
        Fp link, rhs;
        mont_mul(link, uv[1], fv[4], f);
        mont_mul(link, link, fv[5], f);
        mont_mul(rhs, uv[3], gv[4], f);
        mont_mul(rhs, rhs, gv[5], f);
        sub_mod(link, link, rhs, f);
        // partial-product definition constraints
        Fp c_u1, c_u2, c_v1, c_v2;
        mont_mul(t, zi, fv[0], f);
        mont_mul(t, t, fv[1], f);
        sub_mod(c_u1, uv[0], t, f);
        mont_mul(t, uv[0], fv[2], f);
        mont_mul(t, t, fv[3], f);
        sub_mod(c_u2, uv[1], t, f);
        mont_mul(t, zwi, gv[0], f);
        mont_mul(t, t, gv[1], f);
        sub_mod(c_v1, uv[2], t, f);
        mont_mul(t, uv[2], gv[2], f);
        mont_mul(t, t, gv[3], f);
        sub_mod(c_v2, uv[3], t, f);

        // lookup (LogUp)
        Fp ba, bt, dphi, lk;
        add_mod(ba, beta_lk, w[5], f);
        add_mod(bt, beta_lk, fx[8], f);
        sub_mod(dphi, phiwi, phii, f);
        mont_mul(lk, dphi, ba, f);
        mont_mul(lk, lk, bt, f);
        sub_mod(lk, lk, bt, f);
        Fp mba;
        mont_mul(mba, mi, ba, f);
        add_mod(lk, lk, mba, f);

        // total = gate + α·link + α²·l0·(z−1) + α³·lk + α⁴·l0·φ
        //       + α⁵·c_u1 + α⁶·c_u2 + α⁷·c_v1 + α⁸·c_v2
        Fp total = gate;
        mont_mul(t, ap[1], link, f);
        add_mod(total, total, t, f);
        Fp zm1;
        sub_mod(zm1, zi, f.one, f);
        mont_mul(t, ap[2], l0i, f);
        mont_mul(t, t, zm1, f);
        add_mod(total, total, t, f);
        mont_mul(t, ap[3], lk, f);
        add_mod(total, total, t, f);
        mont_mul(t, ap[4], l0i, f);
        mont_mul(t, t, phii, f);
        add_mod(total, total, t, f);
        mont_mul(t, ap[5], c_u1, f);
        add_mod(total, total, t, f);
        mont_mul(t, ap[6], c_u2, f);
        add_mod(total, total, t, f);
        mont_mul(t, ap[7], c_v1, f);
        add_mod(total, total, t, f);
        mont_mul(t, ap[8], c_v2, f);
        add_mod(total, total, t, f);

        mont_mul(total, total, zhi, f);
        Fp out_std;
        from_mont(out_std, total, f);
        std::memcpy(t_out + 4 * i, out_std.v, 32);
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Clos-network routing planner (ops/clos.py's native twin).
//
// Decomposes a static permutation of E = 2^e slots into lane-permutation
// stages executable at streaming speed on TPU (see protocol_tpu/ops/clos.py
// for the network structure). The level decomposition assigns each edge of
// the 128-regular bipartite row multigraph a color (= middle subnetwork) via
// recursive Euler halving; colors give the input/output lane-permutation
// stages and the recursive middle sub-permutations.
//
// The reference has no counterpart (its trust matrix is 4x4); this planner
// exists to make the 10M-peer SpMV run as vector shuffles instead of
// scalar-unit gathers.

namespace clos_planner {

typedef int32_t i32;
typedef int64_t i64;
typedef uint8_t u8;
typedef uint32_t u32;

// Shared scratch, sized once for the top level and reused at every level
// (deeper levels only touch prefixes). The walk arrays are split-local
// (indexed by local edge id) so the Euler chase stays in the smallest
// possible working set.
// Ask the kernel for 2 MB pages on a freshly-reserved buffer: random
// access into the GB-scale walk arrays otherwise pays a 4 KB TLB miss
// + page walk on top of each DRAM miss. Portable best-effort: the r5
// measurement box (Firecracker microVM) ACCEPTS the advise but never
// materializes huge pages (AnonHugePages stays 0, plan times
// unchanged) — on hosts with working THP this is a known multi-x TLB
// lever for the walk; keep the call sites and re-measure per box.
static void advise_huge(void *p, size_t bytes) {
#ifdef __linux__
    uintptr_t a = ((uintptr_t)p + 4095) & ~(uintptr_t)4095;
    uintptr_t e = ((uintptr_t)p + bytes) & ~(uintptr_t)4095;
    if (e > a && e - a >= (2u << 20))
        madvise((void *)a, e - a, MADV_HUGEPAGE);
#else
    (void)p;
    (void)bytes;
#endif
}

struct ColorScratch {
    std::vector<i32> eids;     // edge ids, partitioned in place
    std::vector<i32> tmp;      // partition buffer
    std::vector<i32> ls, rs;   // pre-gathered endpoints per local edge
    std::vector<i32> ladj, radj;
    std::vector<i32> lpart, rpart, seg_of;
    std::vector<i32> lcur, rcur;
    std::vector<i64> lptr, rptr;
    std::vector<u8> used, side_a;
    // cache-layout fusion for the interleaved walk (r4): the walk's
    // per-step DRAM misses dominate plan wall-clock on 1-core hosts.
    // pairs[j] packs (lpart, rpart) in ONE 8-byte word (one line feeds
    // both involutions) and meta[j] packs (seg<<2 | colored<<1 | side)
    // — ~5-6 dependent misses per step collapse to ~2.
    std::vector<u64> pairs;
    std::vector<u32> meta;
    // lcur/rcur double as the fused build's pend arrays; they hold -1
    // everywhere between euler_split calls (every vertex pairs off —
    // degrees are even), so they are filled ONCE here and only after a
    // cursor-fallback clobber (pend_clean). Refilling the m-sized
    // arrays per small split would dominate deep recursion levels.
    bool pend_clean = false;

    void ensure(i64 El, i64 m) {
        if ((i64)eids.size() < El) {
            // madvise must land BEFORE first touch (resize's zero-fill
            // faults the pages): reserve → advise → resize, so the
            // fill faults 2 MB pages directly. The walk's
            // random-access arrays are the TLB-critical set.
            auto prep = [El](auto &v) {
                v.reserve(El);
                advise_huge(v.data(),
                            (size_t)El * sizeof(*v.data()));
                v.resize(El);
            };
            prep(eids);
            prep(tmp);
            prep(ls);
            prep(rs);
            prep(ladj);
            prep(radj);
            prep(used);
            prep(lpart);
            prep(rpart);
            prep(seg_of);
            prep(side_a);
            prep(pairs);
            prep(meta);
        }
        if ((i64)lptr.size() < m + 1) {
            lptr.resize(m + 1); rptr.resize(m + 1);
            lcur.resize(m); rcur.resize(m);
            pend_clean = false;  // fresh elements are uninitialized
        }
    }
};

// 2-color the subset eids[lo..hi) of an even-regular bipartite multigraph
// so every vertex's incident edges split evenly; stable-partition side-A
// first and return its size. i_src: per-edge left vertex; right vertex =
// eid >> 7.
//
// Pairing formulation: pair each vertex's incident edges (two involutions
// lpart/rpart on the subset). Alternating the two pairings yields cycles
// of even length (links alternate between two involutions), and a proper
// 2-coloring along each cycle halves every vertex's degree. Traversal is
// orbit-walking of succ = rpart∘lpart — two dependent loads per step —
// interleaved across K walkers for memory-level parallelism. Walkers may
// land on the same cycle with arbitrary phase; each collision records a
// parity constraint between the two segments, and a final union pass
// flips whole segments to satisfy all constraints (consistent because a
// global proper 2-coloring exists; verified, with a cursor-walk fallback
// if the check ever failed).
static void euler_split_cursor(const i32 *ls, const i32 *rs,
                               ColorScratch &S, i64 k, i64 m);

// CLOS_SPLIT_DEBUG=1: per-phase nanosecond accumulators across every
// euler_split call (all threads), printed by clos_plan — the evidence
// for where plan wall-clock actually goes (r5: the adjacency/pairing
// build vs the orbit walk).
struct SplitPhaseNanos {
    std::atomic<i64> build{0}, walk{0}, finish{0};
};
static SplitPhaseNanos g_split_nanos;

static inline i64 _now_ns() {
    return std::chrono::duration_cast<std::chrono::nanoseconds>(
        std::chrono::steady_clock::now().time_since_epoch()).count();
}

static void build_adjacency(const i32 *ls, const i32 *rs,
                            ColorScratch &S, i64 k, i64 m) {
    // counting-sort CSR build (lptr/rptr/ladj/radj) — the cursor
    // walk's structure; the large-split path no longer needs it
    i64 *lptr = S.lptr.data();
    i64 *rptr = S.rptr.data();
    std::fill(lptr, lptr + m + 1, 0);
    std::fill(rptr, rptr + m + 1, 0);
    for (i64 j = 0; j < k; ++j) {
        lptr[ls[j] + 1]++;
        rptr[rs[j] + 1]++;
    }
    for (i64 v = 0; v < m; ++v) {
        lptr[v + 1] += lptr[v];
        rptr[v + 1] += rptr[v];
    }
    i32 *lcur = S.lcur.data();
    i32 *rcur = S.rcur.data();
    for (i64 v = 0; v < m; ++v) {
        lcur[v] = (i32)lptr[v];
        rcur[v] = (i32)rptr[v];
    }
    i32 *ladj = S.ladj.data();
    i32 *radj = S.radj.data();
    for (i64 j = 0; j < k; ++j) {
        ladj[lcur[ls[j]]++] = (i32)j;
        radj[rcur[rs[j]]++] = (i32)j;
    }
}

static i64 euler_split(const i32 *i_src, ColorScratch &S, i64 lo, i64 hi,
                       i64 m) {
    const bool dbg = std::getenv("CLOS_SPLIT_DEBUG") != nullptr;
    i64 t0 = dbg ? _now_ns() : 0;
    i64 k = hi - lo;
    i32 *e = S.eids.data() + lo;
    i32 *ls = S.ls.data();
    i32 *rs = S.rs.data();
    u8 *side_a = S.side_a.data();   // pre-flip color: member=1, lpart=0

    {
    // FUSED pairing build (r5): pair each vertex's incident edges by
    // ARRIVAL order in one streaming pass — any perfect per-vertex
    // matching yields the even alternating cycles the halving needs,
    // so the counting-sort CSR (histogram + prefix + two scatter
    // passes into E-sized ladj/radj, ~4 random accesses per edge) is
    // dead weight on this path. pend[v] holds the unmatched edge at
    // vertex v (degrees are even, so none remain). pairs[j] packs
    // (lpart, rpart) in ONE 8-byte word (r4: one line feeds both
    // involutions in the walk).
    u64 *pairs = S.pairs.data();
    i32 *pendL = S.lcur.data();  // m-sized scratch, free on this path
    i32 *pendR = S.rcur.data();
    if (!S.pend_clean) {
        std::fill(pendL, pendL + S.lcur.size(), -1);
        std::fill(pendR, pendR + S.rcur.size(), -1);
        S.pend_clean = true;
    }
    for (i64 j = 0; j < k; ++j) {
        i32 eid = e[j];
        i32 v = i_src[eid];
        i32 w = eid >> 7;
        i32 &pl = pendL[v];
        if (pl < 0) {
            pl = (i32)j;
        } else {
            pairs[pl] = (pairs[pl] & ~(u64)0xffffffffu) | (u32)j;
            pairs[j] = (pairs[j] & ~(u64)0xffffffffu) | (u32)pl;
            pl = -1;
        }
        i32 &pr = pendR[w];
        if (pr < 0) {
            pr = (i32)j;
        } else {
            pairs[pr] = (pairs[pr] & 0xffffffffu) | ((u64)(u32)j << 32);
            pairs[j] = (pairs[j] & 0xffffffffu) | ((u64)(u32)pr << 32);
            pr = -1;
        }
    }
    auto lpart_of = [&](i64 j) -> i32 { return (i32)(u32)pairs[j]; };
    auto rpart_of = [&](i64 j) -> i32 { return (i32)(pairs[j] >> 32); };
    if (dbg) {
        g_split_nanos.build.fetch_add(_now_ns() - t0);
        t0 = _now_ns();
    }

    if (k < (1 << 16)) {
        // cache-resident splits: one sequential walker colors each
        // alternating cycle end to end — no collisions, so none of the
        // interleaved path's segment/constraint bookkeeping (r5; the
        // r4 small path built a full counting-sort CSR + cursor walk)
        u8 *used = S.used.data();
        std::fill(used, used + k, (u8)0);
        for (i64 s0 = 0; s0 < k; ++s0) {
            if (used[s0]) continue;
            i32 cur = (i32)s0;
            used[s0] = 1;
            side_a[s0] = 1;
            for (;;) {
                i32 p = lpart_of(cur);
                used[p] = 1;
                side_a[p] = 0;
                i32 nxt = rpart_of(p);
                if (nxt == (i32)s0) break;
                used[nxt] = 1;
                side_a[nxt] = 1;
                cur = nxt;
            }
        }
        if (dbg) g_split_nanos.walk.fetch_add(_now_ns() - t0);
        goto partition;
    }

    // per-edge walk state fused into one word: seg<<2 | colored<<1 |
    // side — the three former arrays (used/seg_of/side_a) cost three
    // independent misses per claimed edge; meta costs one.
    u32 *meta = S.meta.data();
    std::memset(meta, 0, (size_t)k * sizeof(u32));
    auto is_colored = [&](i64 j) -> bool { return meta[j] & 2u; };

    // segments + parity constraints between them
    struct Seg { i32 start; i32 members; i32 lparts; };
    struct Con { i32 a, b; u8 parity; };  // flip[a] ^ flip[b] == parity
    std::vector<Seg> segs;
    std::vector<Con> cons;

    const int K = 32;  // MLP depth: each step chains ~2 misses, so 32
                       // walkers keep ~16 loads in flight
    struct Walker { i32 cur; i32 start; i32 seg; i32 members; i32 lparts;
                    bool active; };
    Walker ws[K];
    for (int w = 0; w < K; ++w) ws[w].active = false;
    i64 scan = 0;
    int n_active = 0;

    auto finish = [&](Walker &w) {
        segs[w.seg].members = w.members;
        segs[w.seg].lparts = w.lparts;
        w.active = false;
    };
    auto launch = [&](Walker &w) -> bool {
        while (scan < k && is_colored(scan)) ++scan;
        if (scan >= k) return false;
        w.cur = (i32)scan;
        w.start = (i32)scan;
        w.seg = (i32)segs.size();
        segs.push_back({w.start, 1, 0});
        // color the start as a member immediately so no other walker can
        // traverse onto it half-claimed
        meta[w.cur] = ((u32)w.seg << 2) | 2u | 1u;  // colored, side=1
        // the start's BACKWARD rpart link is the one link no traversal
        // will check when its partner was claimed first — record its
        // alternation constraint here (duplicates are consistent)
        i32 back = rpart_of(w.start);
        if (is_colored(back))
            cons.push_back({w.seg, (i32)(meta[back] >> 2),
                            (u8)(meta[back] & 1u)});
        w.members = 1;
        w.lparts = 0;
        w.active = true;
        ++scan;
        return true;
    };
    for (int w = 0; w < K; ++w) {
        if (launch(ws[w])) ++n_active;
        else break;
    }

    while (n_active > 0) {
        for (int wi = 0; wi < K; ++wi) {
            Walker &w = ws[wi];
            if (!w.active) continue;
            // one step: claim cur's lpart, then the next member
            i32 p = lpart_of(w.cur);
            u32 mp = meta[p];
            if (mp & 2u) {
                // seam on the lpart link: final(p) must be != member(1)
                cons.push_back({w.seg, (i32)(mp >> 2), (u8)(mp & 1u)});
                finish(w);
                if (!launch(w)) --n_active;
                continue;
            }
            meta[p] = ((u32)w.seg << 2) | 2u;  // colored, side=0
            ++w.lparts;
            i32 nxt = rpart_of(p);
            if (nxt == w.start) {     // own cycle closed, consistent
                finish(w);
                if (!launch(w)) --n_active;
                continue;
            }
            u32 mn = meta[nxt];
            if (mn & 2u) {
                // seam on the rpart link: final(nxt) must be != lpart(0)
                cons.push_back({w.seg, (i32)(mn >> 2),
                                (u8)((mn & 1u) ^ 1u)});
                finish(w);
                if (!launch(w)) --n_active;
                continue;
            }
            meta[nxt] = ((u32)w.seg << 2) | 2u | 1u;  // colored, side=1
            ++w.members;
            __builtin_prefetch(&pairs[nxt]);
            w.cur = nxt;
        }
    }

    if (dbg) {
        g_split_nanos.walk.fetch_add(_now_ns() - t0);
        t0 = _now_ns();
    }
    // solve segment flips: BFS over the constraint graph with parity
    // (flat CSR adjacency — per-segment std::vectors were allocation
    // churn at 32-walker segment counts)
    i64 ns = (i64)segs.size();
    i64 nc = (i64)cons.size();
    bool ok = true;
    for (const Con &c : cons)
        if (c.a < 0 || c.a >= ns || c.b < 0 || c.b >= ns) {
            ok = false;  // should be impossible; defensive
            break;
        }
    std::vector<i32> cptr(ns + 1, 0), cadj;
    std::vector<u8> cpar;
    std::vector<int8_t> flip(ns, -1);
    if (ok) {
        for (const Con &c : cons) {
            cptr[c.a + 1]++;
            cptr[c.b + 1]++;
        }
        for (i64 s = 0; s < ns; ++s) cptr[s + 1] += cptr[s];
        cadj.resize(2 * nc);
        cpar.resize(2 * nc);
        std::vector<i32> ccur(cptr.begin(), cptr.end() - 1);
        for (const Con &c : cons) {
            cadj[ccur[c.a]] = c.b;
            cpar[ccur[c.a]++] = c.parity;
            cadj[ccur[c.b]] = c.a;
            cpar[ccur[c.b]++] = c.parity;
        }
        std::vector<i32> queue;
        for (i64 s0 = 0; s0 < ns && ok; ++s0) {
            if (flip[s0] >= 0) continue;
            flip[s0] = 0;
            queue.clear();
            queue.push_back((i32)s0);
            while (!queue.empty() && ok) {
                i32 cur = queue.back();
                queue.pop_back();
                for (i32 p = cptr[cur]; p < cptr[cur + 1]; ++p) {
                    int8_t want = (int8_t)(flip[cur] ^ cpar[p]);
                    if (flip[cadj[p]] < 0) {
                        flip[cadj[p]] = want;
                        queue.push_back(cadj[p]);
                    } else if (flip[cadj[p]] != want) {
                        ok = false;  // impossible; fallback below
                        break;
                    }
                }
            }
        }
    }
    if (!ok) {
        // correctness fallback needs ls/rs and the CSR the fused path
        // skips; building them clobbers the lcur/rcur pend invariant
        for (i64 j = 0; j < k; ++j) {
            ls[j] = i_src[e[j]];
            rs[j] = e[j] >> 7;
        }
        build_adjacency(ls, rs, S, k, m);
        S.pend_clean = false;
        euler_split_cursor(ls, rs, S, k, m);   // recompute side_a exactly
    } else {
        // apply flips in ONE streaming pass: meta[j] already carries
        // (seg, side), so the final side is side ^ flip[seg] — the r4
        // code re-WALKED every flipped segment (2 random loads per
        // edge, a second walk's worth of DRAM misses) to do this
        for (i64 j = 0; j < k; ++j)
            side_a[j] = (u8)((meta[j] & 1u)
                             ^ (u8)flip[meta[j] >> 2]);
    }

    }

partition:
    // stable partition: side-A edges first
    {
    i32 *tmp = S.tmp.data();
    i64 na = 0;
    for (i64 j = 0; j < k; ++j)
        if (side_a[j]) tmp[na++] = e[j];
    i64 nb = na;
    for (i64 j = 0; j < k; ++j)
        if (!side_a[j]) tmp[nb++] = e[j];
    std::copy(tmp, tmp + k, e);
    if (dbg && k >= (1 << 16))
        g_split_nanos.finish.fetch_add(_now_ns() - t0);
    return na;
    }
}

// Original cursor-based Euler walk (sequential, no pairing) — retained
// as the correctness fallback for euler_split. ls/rs and the CSR in S
// are already built by the caller; only cursors need resetting. Writes
// side_a for the subset; the caller partitions.
static void euler_split_cursor(const i32 *ls, const i32 *rs,
                               ColorScratch &S, i64 k, i64 m) {
    const i64 *lptr = S.lptr.data();
    const i64 *rptr = S.rptr.data();
    i32 *lcur = S.lcur.data();
    i32 *rcur = S.rcur.data();
    const i32 *ladj = S.ladj.data();
    const i32 *radj = S.radj.data();
    for (i64 v = 0; v < m; ++v) {
        lcur[v] = (i32)lptr[v];
        rcur[v] = (i32)rptr[v];
    }
    u8 *used = S.used.data();
    u8 *side_a = S.side_a.data();
    std::memset(used, 0, k);

    for (i64 start = 0; start < k; ++start) {
        if (used[start]) continue;
        i32 v = ls[start];
        bool on_left = true;
        u8 parity = 1;
        for (;;) {
            i32 eid = -1;
            if (on_left) {
                while (lcur[v] < (i32)lptr[v + 1]) {
                    i32 cand = ladj[lcur[v]++];
                    if (!used[cand]) { eid = cand; break; }
                }
            } else {
                while (rcur[v] < (i32)rptr[v + 1]) {
                    i32 cand = radj[rcur[v]++];
                    if (!used[cand]) { eid = cand; break; }
                }
            }
            if (eid < 0) break;
            used[eid] = 1;
            side_a[eid] = parity;
            parity ^= 1;
            v = on_left ? rs[eid] : ls[eid];
            on_left = !on_left;
        }
    }

}

// Color the r-regular bipartite multigraph (r a power of two) with r
// colors; writes color[eid] for local edge ids 0..El.
static void color_edges(const i32 *i_src, i64 El, i64 m, i32 r,
                        ColorScratch &S, u8 *color) {
    S.ensure(El, m);
    for (i64 j = 0; j < El; ++j) S.eids[j] = (i32)j;
    struct Frame { i64 lo, hi; i32 d; u8 c0; };
    std::vector<Frame> stack;
    stack.push_back({0, El, r, 0});
    while (!stack.empty()) {
        Frame f = stack.back();
        stack.pop_back();
        if (f.d == 1) {
            for (i64 j = f.lo; j < f.hi; ++j) color[S.eids[j]] = f.c0;
            continue;
        }
        i64 na = euler_split(i_src, S, f.lo, f.hi, m);
        stack.push_back({f.lo, f.lo + na, f.d / 2, f.c0});
        stack.push_back({f.lo + na, f.hi, f.d / 2, (u8)(f.c0 + f.d / 2)});
    }
}

struct PlanCtx {
    u8 *stages;            // (2*nlevels-1) arrays of E bytes each
    i64 E;
    const i32 *bits;
    i32 nlevels;
};

// per-walker scratch: the recursion below a fork point runs entirely in
// one of these, so independent sub-splits can run on separate threads
struct SubScratch {
    std::vector<std::vector<i32>> mid;    // per-level middle perms
    std::vector<i32> isrc;
    std::vector<u8> color;
    ColorScratch cscratch;

    void ensure(i64 El, i32 level, i32 nlevels) {
        if ((i64)isrc.size() < El) {
            isrc.resize(El);
            color.resize(El);
        }
        cscratch.ensure(El, El >> 7);
        if ((i64)mid.size() < (size_t)nlevels) mid.resize(nlevels);
        i64 sz = El;
        for (i32 l = level; l < nlevels - 1; ++l) {
            if ((i64)mid[l].size() < sz) mid[l].resize(sz);
            sz >>= 7;
        }
    }
};

static void plan_rec(PlanCtx &C, SubScratch &S, const i32 *perm_l, i64 El,
                     i64 slot_off, i32 level) {
    auto t_enter = std::chrono::steady_clock::now();  // level-0 debug only
    i32 nstages = 2 * C.nlevels - 1;
    if (level == C.nlevels - 1) {
        i32 r = 1 << C.bits[level];
        u8 *st = C.stages + (i64)level * C.E;
        for (i64 d = 0; d < El; ++d) {
            i64 sl = slot_off + d;
            st[sl] = (u8)(((sl & 127) & ~(i64)(r - 1)) + perm_l[d]);
        }
        return;
    }
    i64 ml = El >> 7;
    i32 *isrc = S.isrc.data();
    for (i64 d = 0; d < El; ++d) isrc[d] = perm_l[d] >> 7;
    u8 *color = S.color.data();
    color_edges(isrc, El, ml, 128, S.cscratch, color);

    u8 *st_in = C.stages + (i64)level * C.E;
    u8 *st_out = C.stages + (i64)(nstages - 1 - level) * C.E;
    i32 *mid = S.mid[level].data();
    for (i64 d = 0; d < El; ++d) {
        i64 i = isrc[d];
        i64 k = color[d];
        st_in[slot_off + i * 128 + k] = (u8)(perm_l[d] & 127);
        st_out[slot_off + d] = (u8)k;
        mid[k * ml + (d >> 7)] = (i32)i;
    }
    if (level == 0 && C.nlevels > 2) {
        // the 128 sub-splits are independent (disjoint slot ranges):
        // fan them out across hardware threads, each with its own
        // scratch. The level-0 coloring above is the serial fraction
        // (1/nlevels of total coloring work).
        // CLOS_PLAN_DEBUG=1: per-phase breakdown (serial level-0 vs
        // the parallelizable sub-splits) to stderr — the measured
        // fan-out evidence on affinity-capped 1-core hosts where the
        // thread pool cannot show wall-clock speedup.
        const bool plan_dbg = std::getenv("CLOS_PLAN_DEBUG") != nullptr;
        auto tsplit0 = std::chrono::steady_clock::now();
        unsigned nt = 0;
        if (const char *env = std::getenv("CLOS_PLAN_THREADS"))
            nt = (unsigned)std::atoi(env);
        if (!nt) {
#ifdef __linux__
            // the AFFINITY count, not hardware_concurrency: containers
            // often expose all host threads while pinning one core, and
            // oversubscribing the cache-hostile walk is ~3x slower
            cpu_set_t set;
            if (sched_getaffinity(0, sizeof(set), &set) == 0)
                nt = (unsigned)CPU_COUNT(&set);
#endif
            if (!nt) nt = std::thread::hardware_concurrency();
        }
        if (nt > 16) nt = 16;
        if (nt > 1) {
            std::atomic<i64> next(0);
            auto worker = [&]() {
                SubScratch local;
                local.ensure(ml, 1, C.nlevels);
                for (;;) {
                    i64 k = next.fetch_add(1);
                    if (k >= 128) break;
                    plan_rec(C, local, mid + k * ml, ml,
                             slot_off + k * ml, 1);
                }
            };
            std::vector<std::thread> pool;
            for (unsigned t = 0; t < nt; ++t)
                pool.emplace_back(worker);
            for (auto &th : pool) th.join();
            if (plan_dbg) {
                double serial = std::chrono::duration<double>(
                    tsplit0 - t_enter).count();
                double par = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - tsplit0).count();
                std::fprintf(stderr,
                             "clos_plan E=%lld: serial level-0 %.2fs, "
                             "128 sub-splits %.2fs on %u thread(s)\n",
                             (long long)El, serial, par, nt);
            }
            return;
        }
        if (plan_dbg) {
            // serial path: per-split walltimes prove the independent-
            // split structure the pool exploits on multicore hosts
            double serial = std::chrono::duration<double>(
                tsplit0 - t_enter).count();
            double tmin = 1e30, tmax = 0, tsum = 0;
            for (i64 k = 0; k < 128; ++k) {
                auto k0 = std::chrono::steady_clock::now();
                plan_rec(C, S, mid + k * ml, ml, slot_off + k * ml, 1);
                double dk = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - k0).count();
                tsum += dk;
                if (dk < tmin) tmin = dk;
                if (dk > tmax) tmax = dk;
            }
            std::fprintf(stderr,
                         "clos_plan E=%lld: serial level-0 %.2fs; 128 "
                         "independent sub-splits %.2fs total "
                         "(min %.3fs max %.3fs per split -> ideal "
                         "16-thread tail %.2fs)\n",
                         (long long)El, serial, tsum, tmin, tmax,
                         tsum / 16 + tmax);
            return;
        }
    }
    for (i64 k = 0; k < 128; ++k)
        plan_rec(C, S, mid + k * ml, ml, slot_off + k * ml, level + 1);
}

}  // namespace clos_planner

extern "C" {

// Plan a Clos route for permutation perm (y[d] = x[perm[d]]).
// perm: int32[E], E = 1<<e a power of two >= 128; bits: per-level radix
// bits, interior levels must be 7, sum == e. stages_out:
// uint8[(2*nlevels-1)*E]. Returns 0 ok, 1 not a permutation, 2 bad bits.
int clos_plan(const int32_t *perm, int64_t E, const int32_t *bits,
              int32_t nlevels, uint8_t *stages_out) {
    using namespace clos_planner;
    int e = 0;
    while (((i64)1 << e) < E) ++e;
    if (((i64)1 << e) != E || e < 7) return 2;
    i64 sum = 0;
    for (i32 l = 0; l < nlevels; ++l) {
        if (l < nlevels - 1 && bits[l] != 7) return 2;
        if (bits[l] < 1 || bits[l] > 7) return 2;
        sum += bits[l];
    }
    if (sum != e) return 2;

    {   // bijection check
        std::vector<u8> seen(E, 0);
        for (i64 d = 0; d < E; ++d) {
            i32 s = perm[d];
            if (s < 0 || s >= E || seen[s]) return 1;
            seen[s] = 1;
        }
    }

    PlanCtx C;
    C.stages = stages_out;
    C.E = E;
    C.bits = bits;
    C.nlevels = nlevels;
    SubScratch S;
    if (nlevels > 1) S.ensure(E, 0, nlevels);
    else S.mid.resize(1);
    plan_rec(C, S, perm, E, 0, 0);
    if (std::getenv("CLOS_SPLIT_DEBUG")) {
        std::fprintf(stderr,
                     "clos_split phases (large splits, all levels): "
                     "build %.2fs walk %.2fs finish %.2fs\n",
                     g_split_nanos.build.load() * 1e-9,
                     g_split_nanos.walk.load() * 1e-9,
                     g_split_nanos.finish.load() * 1e-9);
        g_split_nanos.build = 0;
        g_split_nanos.walk = 0;
        g_split_nanos.finish = 0;
    }
    return 0;
}

// Replay a finished plan on int32 data (y = route(x)) — the native
// twin of ops/clos.py apply_route_np, used for plan VALIDATION: the
// numpy replay (take_along_axis + swapaxes copies over 13 stages of
// 2^28 slots) costs ~1/5 of the 10M plan itself; this fused
// gather+interleave version runs at memcpy-ish speed. x is modified
// in place; tmp must be E int32s of scratch. Returns 0, or 2 for a
// bad E/bits combination (same contract as clos_plan).
int clos_apply_route(const uint8_t *stages, int64_t E,
                     const int32_t *bits, int32_t nlevels,
                     int32_t *x, int32_t *tmp) {
    using namespace clos_planner;
    int e = 0;
    while (((i64)1 << e) < E) ++e;
    if (((i64)1 << e) != E || e < 7) return 2;
    i64 sum = 0;
    for (i32 l = 0; l < nlevels; ++l) {
        // same schedule contract as clos_plan: interior levels are
        // the 128-lane radix, the base level 1..7 bits — anything
        // else must error, not replay garbage
        if (l < nlevels - 1 && bits[l] != 7) return 2;
        if (bits[l] < 1 || bits[l] > 7) return 2;
        sum += bits[l];
    }
    if (sum != e) return 2;
    i32 nstages = 2 * nlevels - 1;
    i32 si = 0;
    i32 *x_orig = x;
    // forward levels: lane gather within 128-rows, then the (B, m,
    // 128) -> (B, 128, m) interleave, FUSED into one scatter pass
    for (i32 li = 0; li < nlevels - 1; ++li) {
        const u8 *st = stages + (i64)si * E;
        i64 m = E >> (7 * (li + 1));
        i64 nB = (i64)1 << (7 * li);
        for (i64 b = 0; b < nB; ++b) {
            const i32 *xb = x + b * m * 128;
            i32 *tb = tmp + b * m * 128;
            const u8 *sb = st + b * m * 128;
            for (i64 r = 0; r < m; ++r)
                for (i64 l = 0; l < 128; ++l)
                    tb[l * m + r] = xb[r * 128 + sb[r * 128 + l]];
        }
        std::swap(x, tmp);
        ++si;
    }
    {   // middle stage: plain within-row gather
        const u8 *st = stages + (i64)si * E;
        for (i64 r = 0; r < E >> 7; ++r)
            for (i64 l = 0; l < 128; ++l)
                tmp[r * 128 + l] = x[r * 128 + st[r * 128 + l]];
        std::swap(x, tmp);
        ++si;
    }
    // reverse levels: inverse interleave fused with the gather
    for (i32 li = nlevels - 2; li >= 0; --li) {
        const u8 *st = stages + (i64)si * E;
        i64 m = E >> (7 * (li + 1));
        i64 nB = (i64)1 << (7 * li);
        for (i64 b = 0; b < nB; ++b) {
            const i32 *xb = x + b * m * 128;
            i32 *tb = tmp + b * m * 128;
            const u8 *sb = st + b * m * 128;
            // in (B, 128, m) -> out (B, m, 128) then gather within rows
            for (i64 r = 0; r < m; ++r)
                for (i64 l = 0; l < 128; ++l)
                    tb[r * 128 + l] = xb[(i64)sb[r * 128 + l] * m + r];
        }
        std::swap(x, tmp);
        ++si;
    }
    // one pointer swap per stage: an odd stage count leaves the result
    // in the caller's scratch buffer — copy it home
    if (x != x_orig)
        std::memcpy(x_orig, x, (size_t)E * sizeof(i32));
    return (si == nstages) ? 0 : 2;
}

}  // extern "C"
